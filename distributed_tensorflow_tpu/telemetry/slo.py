"""Declarative serving SLOs with multi-window burn-rate evaluation.

The serving engine (PR 9) measures per-request latency/TTFT; this
module turns those measurements into *objectives* an operator can gate
on — the SRE-workbook formulation:

- an :class:`SLO` declares an **objective** (e.g. 99% of requests) over
  a **condition** (latency under ``threshold_s``, TTFT under
  ``threshold_s``, or plain availability), leaving an **error budget**
  of ``1 - objective``;
- the **burn rate** over a window is ``error_rate / error_budget`` — 1.0
  means the budget is being consumed exactly as fast as it accrues, 14.4
  means a 30-day budget dies in 2 days;
- an SLO **fires** when the burn rate exceeds a window's threshold in
  BOTH the long window and its short confirmation window (the
  multi-window multi-burn-rate rule: the long window gives significance,
  the short one makes the alert reset fast once the problem stops).

Two consumption modes share the math:

- :class:`SLOMonitor` — live: the serving replica feeds each completion
  record; :meth:`SLOMonitor.evaluate` is exported on the health scrape.
- :func:`evaluate_records` / :func:`records_from_events` — post-hoc over
  a run's ``serve.request`` events; ``tools/health_report.py --check``
  gates ``--slo-budget`` on it and ``bench.py --serving`` stamps the
  verdict into its row.

Production window presets live in :data:`DEFAULT_BURN_WINDOWS`; bench
and test runs last seconds, not hours, so :func:`windows_for_span`
scales the preset shape down to the observed span (keeping the 12:1
long:short ratio and the burn thresholds).
"""

from __future__ import annotations

import dataclasses

#: (long_window_s, short_window_s, max_burn_rate) — the SRE-workbook
#: page/ticket pair for a 30-day budget: 1h/5m at 14.4x (2% of budget
#: in 1h) and 6h/30m at 6x (5% of budget in 6h).
DEFAULT_BURN_WINDOWS = ((3600.0, 300.0, 14.4), (21600.0, 1800.0, 6.0))


def default_serving_slos(*, latency_s: float = 0.5,
                         ttft_s: float = 0.25,
                         windows: tuple = DEFAULT_BURN_WINDOWS) -> list:
    """The stock serving objective set (mirrored by the README's SLO
    threshold table): 99% of requests complete under ``latency_s``,
    95% reach their first token under ``ttft_s``, 99.9% complete at
    all."""
    return [
        SLO("p99_latency", "latency", objective=0.99,
            threshold_s=latency_s, windows=windows),
        SLO("p95_ttft", "ttft", objective=0.95,
            threshold_s=ttft_s, windows=windows),
        SLO("availability", "availability", objective=0.999,
            windows=windows),
    ]


def default_online_slos(*, freshness_s: float = 5.0,
                        windows: tuple = DEFAULT_BURN_WINDOWS) -> list:
    """The online-training objective set (ROADMAP item 2, mirrored by
    the README's online SLO table): 90% of published snapshots must be
    servable within ``freshness_s`` of their checkpoint commit
    (update→servable latency — the online counterpart of request
    latency), and 99.9% of snapshot publications succeed. The feed is
    ``stream.snapshot_published`` events
    (:func:`freshness_records_from_events`); the burn math is shared
    with the serving SLOs unchanged."""
    return [
        SLO("freshness_p90", "freshness", objective=0.90,
            threshold_s=freshness_s, windows=windows),
        SLO("snapshot_availability", "availability", objective=0.999,
            windows=windows),
    ]


def windows_for_span(span_s: float) -> tuple:
    """Scale :data:`DEFAULT_BURN_WINDOWS` to a short run: the longest
    window becomes the observed span, every window keeps its shape
    (12:1 long:short) and burn threshold. Windows never collapse below
    1ms so rates stay finite."""
    if span_s <= 0:
        return DEFAULT_BURN_WINDOWS
    scale = span_s / DEFAULT_BURN_WINDOWS[-1][0]
    return tuple((max(1e-3, lw * scale), max(1e-3, sw * scale), burn)
                 for lw, sw, burn in DEFAULT_BURN_WINDOWS)


@dataclasses.dataclass(frozen=True)
class SLO:
    """One declarative objective.

    ``metric``: ``"latency"`` (request dur vs ``threshold_s``),
    ``"ttft"`` (time-to-first-token vs ``threshold_s``),
    ``"freshness"`` (online training's update→servable seconds vs
    ``threshold_s``), or ``"availability"`` (request completed ok).
    ``objective`` is the target good fraction (0.99 → 1% error budget).
    """

    name: str
    metric: str = "latency"
    objective: float = 0.99
    threshold_s: float | None = None
    windows: tuple = DEFAULT_BURN_WINDOWS

    _METRICS = ("latency", "ttft", "availability", "freshness")
    _METRIC_KEYS = {"latency": "latency_s", "ttft": "ttft_s",
                    "freshness": "freshness_s"}

    def __post_init__(self):
        if self.metric not in self._METRICS:
            raise ValueError(f"SLO {self.name}: metric {self.metric!r} "
                             f"not in {self._METRICS}")
        if not 0.0 < self.objective < 1.0:
            raise ValueError(f"SLO {self.name}: objective must be in "
                             f"(0, 1), got {self.objective}")
        if self.metric != "availability" and self.threshold_s is None:
            raise ValueError(f"SLO {self.name}: {self.metric} needs "
                             f"threshold_s")

    @property
    def error_budget(self) -> float:
        return 1.0 - self.objective

    def is_bad(self, record: dict) -> bool:
        """Does one completion record violate the condition?"""
        if self.metric == "availability":
            return not record.get("ok", True)
        v = record.get(self._METRIC_KEYS[self.metric])
        if not isinstance(v, (int, float)):
            # a generation request with no TTFT measurement etc. —
            # treat missing data as bad only for availability
            return False
        return v > self.threshold_s

    @classmethod
    def from_dict(cls, d: dict) -> "SLO":
        windows = d.get("windows")
        return cls(name=d["name"], metric=d.get("metric", "latency"),
                   objective=float(d.get("objective", 0.99)),
                   threshold_s=d.get("threshold_s"),
                   windows=tuple(tuple(w) for w in windows)
                   if windows else DEFAULT_BURN_WINDOWS)


def burn_rate(records: "list[dict]", slo: SLO, *, window_s: float,
              now: float) -> "float | None":
    """Burn rate over ``(now - window_s, now]``: in-window error rate
    divided by the error budget. None with no in-window traffic (no
    evidence — distinct from burn 0.0)."""
    lo = now - window_s
    n = bad = 0
    for r in records:
        w = r.get("wall")
        if not isinstance(w, (int, float)) or not lo < w <= now:
            continue
        n += 1
        bad += bool(slo.is_bad(r))
    if n == 0:
        return None
    return (bad / n) / slo.error_budget


def burn_windows(records: "list[dict]", slo: SLO, *,
                 now: "float | None" = None) -> "list[dict]":
    """Per-window burn snapshot for ONE SLO — the live feed the
    autoscaler (resilience/autoscaler.py) consumes every watch tick.
    Returns the same window dicts :func:`evaluate_records` emits under
    ``windows``: long/short burns plus ``firing`` (BOTH over the
    threshold). ``now`` defaults to the newest record wall."""
    if now is None:
        walls = [r["wall"] for r in records
                 if isinstance(r.get("wall"), (int, float))]
        now = max(walls) if walls else 0.0
    windows = []
    for lw, sw, max_burn in slo.windows:
        bl = burn_rate(records, slo, window_s=lw, now=now)
        bs = burn_rate(records, slo, window_s=sw, now=now)
        windows.append({"long_s": round(lw, 6),
                        "short_s": round(sw, 6),
                        "max_burn": max_burn,
                        "burn_long": bl, "burn_short": bs,
                        "firing": (bl is not None and bs is not None
                                   and bl > max_burn and bs > max_burn)})
    return windows


def evaluate_records(records: "list[dict]", slos: "list[SLO]", *,
                     now: "float | None" = None) -> dict:
    """Evaluate every SLO over completion records.

    Records: ``{"wall": t, "latency_s": s, "ttft_s": s|None, "ok":
    bool}``. Returns per SLO: overall error rate, budget consumed
    (error_rate / budget over the whole record set), per-window burn
    rates, and ``firing`` (any window pair with BOTH burns over its
    threshold). ``now`` defaults to the newest record wall.
    """
    walls = [r["wall"] for r in records
             if isinstance(r.get("wall"), (int, float))]
    if now is None:
        now = max(walls) if walls else 0.0
    out: dict = {}
    for slo in slos:
        n = len(records)
        bad = sum(bool(slo.is_bad(r)) for r in records)
        error_rate = (bad / n) if n else 0.0
        windows = burn_windows(records, slo, now=now)
        firing = any(w["firing"] for w in windows)
        out[slo.name] = {
            "metric": slo.metric,
            "objective": slo.objective,
            "threshold_s": slo.threshold_s,
            "requests": n,
            "bad": bad,
            "error_rate": round(error_rate, 6),
            "budget_consumed": round(error_rate / slo.error_budget, 6),
            "windows": windows,
            "firing": firing,
        }
    return out


def records_from_events(events_by_pid: "dict") -> "list[dict]":
    """Completion records from ``serve.request`` events across every
    process (the post-hoc feed health_report evaluates)."""
    records = []
    for events in events_by_pid.values():
        for ev in events:
            if ev.get("ev") != "serve.request":
                continue
            records.append({
                "wall": ev.get("wall"),
                "latency_s": ev.get("dur_s"),
                "ttft_s": ev.get("ttft_s"),
                "model_version": ev.get("model_version"),
                "tenant": ev.get("tenant"),
                "pclass": ev.get("pclass"),
                "ok": not ev.get("error"),
            })
    records.sort(key=lambda r: r.get("wall") or 0.0)
    return records


def freshness_records_from_events(events_by_pid: "dict") -> "list[dict]":
    """Freshness records measuring true update→**servable** lag.

    A publish event (``stream.snapshot_published`` from the online
    evaluator, or ``rollout.publish`` from the rollout controller)
    opens a freshness interval; it CLOSES only at a serving replica's
    swap-complete event (``serve.swap`` — in-place hot-swap or
    restart adoption, matched by snapshot ``step``), and the record's
    ``freshness_s`` is the publish stamp's own lag plus the
    publish→swap gap. A replica that adopts by restart therefore
    honestly reports the respawn-sized gap the hot-swap path removes;
    a snapshot no replica ever adopts produces NO record (it never
    became servable). One record per adopting replica per publish.

    Back-compat: a run with no ``serve.swap`` events at all (PR 15's
    online topology — the evaluator scores snapshots in-process) keeps
    the original close-at-publish semantics, so existing feeds and the
    ``chaos_sweep --online`` gate read unchanged."""
    pubs, swaps = [], []
    for pid, events in events_by_pid.items():
        for ev in events:
            name = ev.get("ev")
            if name in ("stream.snapshot_published", "rollout.publish"):
                pubs.append(ev)
            elif name == "serve.swap":
                swaps.append((pid, ev))
    records = []
    if not swaps:
        for ev in pubs:
            records.append({
                "wall": ev.get("wall"),
                "freshness_s": ev.get("freshness_s"),
                "lag_events": ev.get("lag_events"),
                "offset": ev.get("offset"),
                "ok": not ev.get("error"),
            })
        records.sort(key=lambda r: r.get("wall") or 0.0)
        return records
    for pub in pubs:
        pwall = pub.get("wall")
        if not isinstance(pwall, (int, float)):
            continue
        step = pub.get("step")
        base = pub.get("freshness_s")
        base = float(base) if isinstance(base, (int, float)) else 0.0
        # each replica's FIRST matching swap at/after the publish
        first: dict = {}
        for pid, sw in swaps:
            if step is not None and sw.get("step") != step:
                continue
            swall = sw.get("wall")
            if not isinstance(swall, (int, float)) or swall < pwall:
                continue
            if pid not in first or swall < first[pid][0]:
                first[pid] = (swall, sw)
        for pid, (swall, sw) in first.items():
            records.append({
                "wall": swall,
                "freshness_s": round(base + (swall - pwall), 6),
                "lag_events": pub.get("lag_events"),
                "offset": pub.get("offset"),
                "step": step,
                "mode": sw.get("mode"),
                "ok": not sw.get("error"),
            })
    records.sort(key=lambda r: r.get("wall") or 0.0)
    return records


class SLOMonitor:
    """Live SLO evaluation over a bounded record window.

    The serving replica calls :meth:`observe` per completion; the
    exporter tick calls :meth:`evaluate` and renders the result on the
    scrape. Keeps the newest ``max_records`` completions — enough to
    cover the longest configured window at serving rates, bounded so a
    week-long replica doesn't grow without limit.
    """

    def __init__(self, slos: "list[SLO]", max_records: int = 8192):
        import collections
        self.slos = list(slos)
        self._records: "collections.deque" = collections.deque(
            maxlen=max_records)

    def observe(self, record: dict):
        self._records.append(dict(record))

    def evaluate(self, now: "float | None" = None) -> dict:
        return evaluate_records(list(self._records), self.slos, now=now)

    def prometheus_lines(self, *, prefix: str = "dtx_",
                         now: "float | None" = None) -> list:
        lines = [f"# TYPE {prefix}slo_burn_rate gauge",
                 f"# TYPE {prefix}slo_budget_consumed gauge",
                 f"# TYPE {prefix}slo_firing gauge"]
        for name, res in self.evaluate(now=now).items():
            lines.append(f'{prefix}slo_budget_consumed{{slo="{name}"}} '
                         f'{res["budget_consumed"]:.6f}')
            lines.append(f'{prefix}slo_firing{{slo="{name}"}} '
                         f'{int(res["firing"])}')
            for w in res["windows"]:
                if w["burn_long"] is not None:
                    lines.append(
                        f'{prefix}slo_burn_rate{{slo="{name}",'
                        f'window="{w["long_s"]:g}s"}} '
                        f'{w["burn_long"]:.6f}')
        return lines
