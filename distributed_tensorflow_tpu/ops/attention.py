"""Fused multi-head attention for TPU (Pallas flash attention).

The reference framework has no fused attention of its own — its BERT /
Transformer workloads run unfused softmax(QK^T)V through stock TF ops
(SURVEY.md §5.7: no flash/blockwise attention anywhere in the reference
tree). On TPU the memory-bound softmax materialisation is the first thing
to kill HBM bandwidth at long sequence length, so the TPU-native framework
makes flash attention a core op: online-softmax tiling in VMEM, MXU-sized
blocks, O(S) memory, with a custom VJP whose backward recomputes
probabilities blockwise from the saved row logsumexp.

Layout convention: ``(batch, num_heads, seq, head_dim)`` throughout.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


# ---------------------------------------------------------------------------
# Reference implementation (also the CPU fallback)
# ---------------------------------------------------------------------------

def length_valid_mask(lengths, q_len: int, kv_len: int, *,
                      causal: bool = False, causal_offset: int | None = None,
                      q_positions=None):
    """Validity mask for right-padded mixed-length batches — the ONE
    masking rule shared by full-sequence recompute (``mha_reference``)
    and the serving engine's incremental KV-cache decode
    (serving/decode.py). Keeping both sides on this function is the
    correctness contract that makes cached decode match full recompute.

    ``lengths``: (B,) true sequence lengths. Query ``i`` of sequence
    ``b`` may see key ``j`` iff both lie inside the sequence
    (``i < lengths[b]`` — via ``q_positions`` when the queries are a
    window into a longer cache — and ``j < lengths[b]``) and, under
    ``causal``, ``j <= i + causal_offset`` (offset defaults to
    ``kv_len - q_len``: bottom-right alignment, the incremental-decode
    case where the single query row sits at the END of the cache).

    Returns (B, 1, q_len, kv_len) bool.
    """
    if causal_offset is None:
        # explicit q_positions are ABSOLUTE cache positions: query p sees
        # key j iff j <= p, no alignment offset
        causal_offset = 0 if q_positions is not None else kv_len - q_len
    lengths = jnp.asarray(lengths, jnp.int32)
    if q_positions is None:
        q_ids = jnp.arange(q_len, dtype=jnp.int32)[None, :]     # (1, q)
    else:
        q_ids = jnp.asarray(q_positions, jnp.int32)
        if q_ids.ndim == 1:
            q_ids = q_ids[:, None]                              # (B, q=1)
    k_ids = jnp.arange(kv_len, dtype=jnp.int32)
    valid = ((q_ids[:, :, None] < lengths[:, None, None])
             & (k_ids[None, None, :] < lengths[:, None, None]))
    if causal:
        valid = valid & (k_ids[None, None, :]
                         <= q_ids[:, :, None] + causal_offset)
    return valid[:, None]                                       # (B,1,q,k)


def mha_reference(q, k, v, *, causal: bool = False, sm_scale: float | None = None,
                  segment_ids=None, lengths=None, q_positions=None):
    """Unfused attention — the semantics contract for the Pallas kernels.

    ``lengths`` (B,) masks a right-padded mixed-length batch via
    :func:`length_valid_mask`: padded keys are invisible to every query
    and fully-padded query rows output 0. ``q_positions`` places the
    queries at explicit cache positions (incremental decode: one query
    at position ``lengths-1`` against a longer key buffer)."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * sm_scale
    valid = None
    if causal and lengths is None:
        qs, ks = q.shape[2], k.shape[2]
        valid = jnp.tril(jnp.ones((qs, ks), dtype=bool), k=ks - qs)[None, None]
    if lengths is not None:
        valid = length_valid_mask(lengths, q.shape[2], k.shape[2],
                                  causal=causal, q_positions=q_positions)
    if segment_ids is not None:
        seg_mask = (segment_ids[:, None, :, None]
                    == segment_ids[:, None, None, :])
        valid = seg_mask if valid is None else valid & seg_mask
    if valid is not None:
        logits = jnp.where(valid, logits, DEFAULT_MASK_VALUE)
    probs = jax.nn.softmax(logits, axis=-1)
    if valid is not None:
        # Fully-masked query rows (causal with q_len > k_len, padded
        # rows) output 0, not the uniform average
        # softmax-of-equal-mask-values would give.
        probs = probs * jnp.any(valid, axis=-1, keepdims=True)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v.astype(jnp.float32)
                      ).astype(q.dtype)


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------

def _block_mask(qb, kb, block_q, block_k, *, causal, causal_offset,
                q_limit=None, k_limit=None):
    """Validity mask for one (q-block, k-block) tile, or None if nothing
    needs masking. Shared by forward and both backward kernels so causal
    alignment and tail padding stay in lockstep across fwd/bwd.

    causal: bottom-right aligned — query i sees key j iff
    j <= i + causal_offset (offset = kv_len - q_len).
    q_limit/k_limit: true (unpadded) lengths; rows/cols past them are
    zero-padding and must not contribute.
    """
    if not causal and q_limit is None and k_limit is None:
        return None
    q_ids = qb * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_ids = kb * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    valid = (q_ids + causal_offset >= k_ids) if causal else (q_ids >= 0)
    if q_limit is not None:
        valid = valid & (q_ids < q_limit)
    if k_limit is not None:
        valid = valid & (k_ids < k_limit)
    return valid


def _fwd_kernel(q_ref, k_ref, v_ref,          # inputs (blocked)
                o_ref, lse_ref,               # outputs
                m_scr, l_scr, acc_scr,        # VMEM scratch
                *, sm_scale: float, causal: bool,
                block_q: int, block_k: int, num_k_blocks: int,
                kv_len: int, causal_offset: int = 0):
    """One (batch·head, q-block, k-block) grid step of flash attention.

    TPU grids run sequentially over the last dimension, so the online
    softmax state (m, l, acc) lives in VMEM scratch carried across the
    k-block steps of one q-block.
    """
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, DEFAULT_MASK_VALUE)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    qb = pl.program_id(1)
    # Under causal masking a k-block strictly above the (bottom-right
    # aligned) diagonal contributes nothing — predicate the step out.
    should_run = ((kb * block_k <= (qb + 1) * block_q - 1 + causal_offset)
                  if causal else kb >= 0)

    @pl.when(should_run)
    def _step():
        q = q_ref[0]                       # (block_q, d)
        k = k_ref[0]                       # (block_k, d)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale

        valid = _block_mask(
            qb, kb, block_q, block_k, causal=causal,
            causal_offset=causal_offset,
            k_limit=kv_len if kv_len % block_k != 0 else None)
        if valid is not None:
            s = jnp.where(valid, s, DEFAULT_MASK_VALUE)

        m_prev = m_scr[:]                  # (block_q, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)             # (block_q, block_k)
        alpha = jnp.exp(m_prev - m_new)    # rescale of previous state
        l_new = alpha * l_scr[:] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new
        l_scr[:] = l_new

    @pl.when(kb == num_k_blocks - 1)
    def _finish():
        l = l_scr[:]
        empty = l == 0.0                   # fully-masked rows -> output 0
        l = jnp.where(empty, 1.0, l)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        # Empty rows store lse = +inf so the backward kernels recompute
        # p = exp(masked_logit - inf) = 0 instead of exp(MASK - MASK) = 1.
        lse_ref[0] = jnp.where(empty, jnp.inf,
                               m_scr[:] + jnp.log(l))   # (block_q, 1)


def _pad_seq(x, multiple):
    """Zero-pad axis 1 (sequence) up to a multiple of ``multiple``."""
    s = x.shape[1]
    pad = (-s) % multiple
    if pad == 0:
        return x
    return jnp.pad(x, ((0, 0), (0, pad), (0, 0)))


def _flash_forward(q, k, v, sm_scale, causal, block_q, block_k, interpret,
                   causal_offset=None):
    batch, heads, q_len, d = q.shape
    k_len = k.shape[2]
    if causal_offset is None:
        causal_offset = k_len - q_len
    block_q = min(block_q, q_len)
    block_k = min(block_k, k_len)
    bh = batch * heads

    qr = _pad_seq(q.reshape(bh, q_len, d), block_q)
    kr = _pad_seq(k.reshape(bh, k_len, d), block_k)
    vr = _pad_seq(v.reshape(bh, k_len, d), block_k)
    qp, kp = qr.shape[1], kr.shape[1]
    nq, nk = qp // block_q, kp // block_k

    grid = (bh, nq, nk)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_k_blocks=nk,
                          kv_len=k_len, causal_offset=causal_offset),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, qp, d), q.dtype),
            jax.ShapeDtypeStruct((bh, qp, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return (out[:, :q_len].reshape(batch, heads, q_len, d),
            lse[:, :q_len].reshape(batch, heads, q_len))


# ---------------------------------------------------------------------------
# Backward kernels (recompute P from saved logsumexp, blockwise)
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_scr,
                   *, sm_scale, causal, block_q, block_k, num_k_blocks,
                   kv_len: int, causal_offset: int = 0):
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    qb = pl.program_id(1)
    should_run = ((kb * block_k <= (qb + 1) * block_q - 1 + causal_offset)
                  if causal else kb >= 0)

    @pl.when(should_run)
    def _step():
        q = q_ref[0]
        kk = k_ref[0]
        vv = v_ref[0]
        # Keep matmul OPERANDS in the input dtype (bf16 in production):
        # fp32 operands run the MXU at half rate, and with head_dim 64
        # already capping utilization at 50% the all-fp32 backward was
        # the single largest off-ideal factor in the step profile.
        # Accumulation stays fp32 via preferred_element_type; only the
        # elementwise softmax-gradient algebra runs in fp32.
        do = do_ref[0]
        lse = lse_ref[0]                   # (block_q, 1)
        delta = delta_ref[0]
        s = jax.lax.dot_general(q, kk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        valid = _block_mask(
            qb, kb, block_q, block_k, causal=causal,
            causal_offset=causal_offset,
            k_limit=kv_len if kv_len % block_k != 0 else None)
        if valid is not None:
            s = jnp.where(valid, s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse)               # (block_q, block_k)
        dp = jax.lax.dot_general(do, vv, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * sm_scale).astype(kk.dtype)
        dq_scr[:] += jax.lax.dot_general(
            ds, kk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kb == num_k_blocks - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr,
                    *, sm_scale, causal, block_q, block_k, num_q_blocks,
                    q_len: int, causal_offset: int = 0):
    qb = pl.program_id(2)

    @pl.when(qb == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    kb = pl.program_id(1)
    # Causal: gradient only flows to k-block kb from q rows at or below
    # the diagonal, i.e. iff max(q_id) >= min(k_id).
    should_run = (((qb + 1) * block_q - 1 + causal_offset >= kb * block_k)
                  if causal else qb >= 0)

    @pl.when(should_run)
    def _step():
        q = q_ref[0]
        kk = k_ref[0]
        vv = v_ref[0]
        # bf16 matmul operands, fp32 accumulation — see _bwd_dq_kernel.
        do = do_ref[0]
        lse = lse_ref[0]                   # (block_q, 1)
        delta = delta_ref[0]
        s = jax.lax.dot_general(q, kk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        valid = _block_mask(
            qb, kb, block_q, block_k, causal=causal,
            causal_offset=causal_offset,
            q_limit=q_len if q_len % block_q != 0 else None)
        if valid is not None:
            s = jnp.where(valid, s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, vv, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qb == num_q_blocks - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_backward(res, g, *, sm_scale, causal, block_q, block_k,
                    interpret, causal_offset=None):
    q, k, v, out, lse = res
    batch, heads, q_len, d = q.shape
    k_len = k.shape[2]
    if causal_offset is None:
        causal_offset = k_len - q_len
    block_q = min(block_q, q_len)
    block_k = min(block_k, k_len)
    bh = batch * heads

    delta = jnp.sum(out.astype(jnp.float32) * g.astype(jnp.float32),
                    axis=-1)                      # (b, h, q_len)

    # Zero-pad to block multiples; padded lse/delta rows are 0 so masked
    # logits give p = exp(MASK - 0) = 0 in the kernels.
    qr = _pad_seq(q.reshape(bh, q_len, d), block_q)
    kr = _pad_seq(k.reshape(bh, k_len, d), block_k)
    vr = _pad_seq(v.reshape(bh, k_len, d), block_k)
    dor = _pad_seq(g.reshape(bh, q_len, d), block_q)
    lser = _pad_seq(lse.reshape(bh, q_len, 1), block_q)
    deltar = _pad_seq(delta.reshape(bh, q_len, 1), block_q)
    qp, kp = qr.shape[1], kr.shape[1]
    nq, nk = qp // block_q, kp // block_k

    q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    row_spec = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_k_blocks=nk,
                          kv_len=k_len, causal_offset=causal_offset),
        grid=(bh, nq, nk),
        in_specs=[
            q_spec,
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            q_spec, row_spec, row_spec,
        ],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((bh, qp, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qr, kr, vr, dor, lser, deltar)

    # dk/dv: grid over k-blocks, inner loop over q-blocks.
    k_spec = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0))
    qj_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, j, 0))
    rowj_spec = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_q_blocks=nq,
                          q_len=q_len, causal_offset=causal_offset),
        grid=(bh, nk, nq),
        in_specs=[qj_spec, k_spec, k_spec, qj_spec, rowj_spec, rowj_spec],
        out_specs=[k_spec, k_spec],
        out_shape=[jax.ShapeDtypeStruct((bh, kp, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, kp, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(qr, kr, vr, dor, lser, deltar)

    shape = (batch, heads, q_len, d)
    kshape = (batch, heads, k_len, d)
    return (dq[:, :q_len].reshape(shape), dk[:, :k_len].reshape(kshape),
            dv[:, :k_len].reshape(kshape))


# ---------------------------------------------------------------------------
# Public op with custom VJP
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_mha(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, _ = _flash_forward(q, k, v, sm_scale, causal, block_q, block_k,
                            interpret)
    return out


def _flash_mha_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, sm_scale, causal, block_q, block_k,
                              interpret)
    return out, (q, k, v, out, lse)


def _flash_mha_bwd(sm_scale, causal, block_q, block_k, interpret, res, g):
    return _flash_backward(res, g, sm_scale=sm_scale, causal=causal,
                           block_q=block_q, block_k=block_k,
                           interpret=interpret)


_flash_mha.defvjp(_flash_mha_fwd, _flash_mha_bwd)


def flash_attention(q, k, v, *, causal: bool = False,
                    sm_scale: float | None = None,
                    block_q: int = 512, block_k: int = 1024,
                    implementation: str | None = None):
    """Fused attention. ``(b, h, s, d)`` in, ``(b, h, s, d)`` out.

    implementation: "pallas" | "reference" | "interpret" | None (auto:
    pallas on TPU, reference elsewhere).
    """
    if implementation is None:
        implementation = ("pallas" if jax.default_backend() == "tpu"
                          else "reference")
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if implementation == "reference":
        return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale)
    interpret = implementation == "interpret"
    return _flash_mha(q, k, v, sm_scale, causal, block_q, block_k, interpret)


def sharded_flash_attention(q, k, v, mesh, *, causal: bool = False,
                            sm_scale: float | None = None,
                            block_q: int = 512, block_k: int = 1024,
                            implementation: str | None = None):
    """``flash_attention`` shard_mapped over the mesh's batch/head axes.

    The Pallas kernel lowers to a Mosaic custom call, which the GSPMD
    partitioner cannot partition: invoked directly inside a partitioned
    jit it forces an all-gather of q/k/v and runs fully replicated on
    every device. Attention is embarrassingly parallel over (batch,
    heads), so run the kernel per-shard under ``shard_map`` over the
    (dcn, dp, fsdp) batch axes and the tp head axis — no collectives
    inside the region.

    Falls back to the plain call when the shard counts don't divide the
    operand dims (then GSPMD's replicated execution is still correct).
    """
    import math

    from jax import shard_map

    from distributed_tensorflow_tpu.cluster.topology import \
        attention_shard_spec

    spec = attention_shard_spec(mesh)
    batch_axes, head_axis = spec[0], spec[1]
    if isinstance(batch_axes, str):   # PartitionSpec flattens 1-tuples
        batch_axes = (batch_axes,)
    n_batch = (math.prod(mesh.shape[a] for a in batch_axes)
               if batch_axes else 1)
    n_head = mesh.shape[head_axis] if head_axis else 1
    if n_batch * n_head == 1 or q.shape[0] % n_batch or q.shape[1] % n_head:
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                               block_q=block_q, block_k=block_k,
                               implementation=implementation)
    fn = functools.partial(flash_attention, causal=causal, sm_scale=sm_scale,
                           block_q=block_q, block_k=block_k,
                           implementation=implementation)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)
