"""Fused vocab-tiled cross-entropy for TPU (Pallas online-logsumexp).

The last-mile inefficiency of the transformer train step: next-token CE
against a tied (vocab, d_model) embedding. The classic path
materializes (tokens, vocab) fp32 logits — for transformer_big at
batch 4 / seq 1024 / vocab 32k that tensor alone is 512 MiB and every
softmax stage round-trips it through HBM, which is why the lax.scan
chunked form (models/transformer.py fused_next_token_loss) runs at
~45-60 % efficiency. These kernels stream the vocab axis through VMEM
flash-attention-style: a logits TILE exists only on-chip, reduced into
a running (max, sumexp) pair, and the backward recomputes each tile's
probabilities from the saved row logsumexp.

≙ the reference's fused softmax-CE lowering
(TF/python/ops/nn_ops.py softmax_cross_entropy_with_logits → fused XLA
reduction) extended to also fuse away the vocab projection itself.

Decomposition (N = flattened tokens, V = vocab, D = d_model):
- forward:  one kernel, grid (N/bn, V/bv): online
            lse_i = logsumexp_v(h_i·E_v) and the target logit
            tl_i = h_i·E_{t_i} picked up by one-hot masking as its tile
            streams by. loss_i = lse_i - tl_i.
- backward: p_adj_iv = (exp(h_i·E_v - lse_i) - 1[v = t_i]) · g_i
            (the softmax-CE gradient, one-hot folded INTO the tile so
            no XLA gather/scatter is needed):
            dh = p_adj @ E      [kernel, grid (N/bn, V/bv)]
            dE = p_adjᵀ @ h     [kernel, grid (V/bv, N/bn)]
  FLOP cost is 5·N·V·D MACs total (vs the scan path's 4) but every
  matmul is MXU-shaped and no (N, V) tensor ever touches HBM.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_BIG = -0.7 * float(jnp.finfo(jnp.float32).max)

# XLA's default scoped-VMEM allowance for custom calls is 16 MiB — a
# conservative slice of the chip's physical VMEM (v5e: 128 MiB). The
# merged backward legitimately wants ~24 MiB (fp32 accumulator scratch
# + double-buffered fp32 alias blocks), so raise the cap for these
# kernels only.
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams",
                           getattr(pltpu, "TPUCompilerParams", None))(
    vmem_limit_bytes=64 * 1024 * 1024)


# ---------------------------------------------------------------------------
# Reference implementation (semantics contract + CPU fallback)
# ---------------------------------------------------------------------------

def ce_reference(hidden, embed, targets):
    """Per-token CE losses, unfused: logsumexp(h@Eᵀ) - (h·E_t)."""
    logits = jnp.einsum("nd,vd->nv", hidden, embed,
                        preferred_element_type=jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    tl = jnp.take_along_axis(logits, targets[:, None], axis=-1)[:, 0]
    return lse - tl


# ---------------------------------------------------------------------------
# Kernels
# ---------------------------------------------------------------------------

def _col_ids(vb, block_n, block_v):
    return vb * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (block_n, block_v), 1)


def _fwd_kernel(h_ref, e_ref, t_ref, lse_ref, tl_ref, m_scr, s_scr, tl_scr,
                *, block_n, block_v, num_v_blocks, vocab_size):
    """Online logsumexp + target-logit pickup over vocab tiles; grid
    (N/bn, V/bv), vocab innermost so state carries in VMEM scratch."""
    vb = pl.program_id(1)

    @pl.when(vb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_BIG)
        s_scr[:] = jnp.zeros_like(s_scr)
        tl_scr[:] = jnp.zeros_like(tl_scr)

    logits = jax.lax.dot_general(
        h_ref[:], e_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)          # (bn, bv)
    cols = _col_ids(vb, block_n, block_v)
    if vocab_size % block_v != 0:
        logits = jnp.where(cols < vocab_size, logits, _NEG_BIG)

    # Target logit: exactly one tile holds column t_i for row i.
    onehot = cols == t_ref[:]                        # (bn, bv), t: (bn,1)
    tl_scr[:] += jnp.sum(jnp.where(onehot, logits, 0.0), axis=1,
                         keepdims=True)

    m_prev = m_scr[:]                                # (bn, 1)
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=1, keepdims=True))
    s_scr[:] = (s_scr[:] * jnp.exp(m_prev - m_new)
                + jnp.sum(jnp.exp(logits - m_new), axis=1, keepdims=True))
    m_scr[:] = m_new

    @pl.when(vb == num_v_blocks - 1)
    def _finish():
        lse_ref[:] = m_scr[:] + jnp.log(s_scr[:])
        tl_ref[:] = tl_scr[:]


def _masked_e(e_ref, vb, block_v, vocab_size):
    """E tile with rows past the vocab end zeroed: those rows are
    UNDEFINED on a padded tail read (NaN in interpret mode) and
    0 * NaN = NaN would poison any contraction over the vocab axis."""
    e = e_ref[:]
    if vocab_size % block_v != 0:
        row = vb * block_v + jax.lax.broadcasted_iota(
            jnp.int32, e.shape, 0)
        e = jnp.where(row < vocab_size, e, 0)
    return e


def _p_adj(h, e, t_ref, lse_ref, g_ref, vb, block_n, block_v, vocab_size):
    """(softmax - onehot(t)) · g for one tile — the CE gradient wrt
    logits, computed in-register from the saved row logsumexp."""
    logits = jax.lax.dot_general(
        h, e, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    p = jnp.exp(logits - lse_ref[:])
    cols = _col_ids(vb, block_n, block_v)
    p = p - (cols == t_ref[:]).astype(jnp.float32)
    if vocab_size % block_v != 0:
        p = jnp.where(cols < vocab_size, p, 0.0)
    return p * g_ref[:]


def _dh_kernel(h_ref, e_ref, t_ref, lse_ref, g_ref, dh_ref, acc_scr,
               *, block_n, block_v, num_v_blocks, vocab_size):
    """dh_i = Σ_v p_adj_iv E_v over vocab tiles; grid (N/bn, V/bv),
    vocab innermost."""
    vb = pl.program_id(1)

    @pl.when(vb == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    e = _masked_e(e_ref, vb, block_v, vocab_size)
    p = _p_adj(h_ref[:], e, t_ref, lse_ref, g_ref, vb, block_n, block_v,
               vocab_size)
    acc_scr[:] += jax.lax.dot_general(
        p.astype(e.dtype), e, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(vb == num_v_blocks - 1)
    def _finish():
        dh_ref[:] = acc_scr[:].astype(dh_ref.dtype)


def _bwd_merged_kernel(h_ref, e_ref, t_ref, lse_ref, g_ref, dh_in_ref,
                       dh_out_ref, de_ref, de_scr,
                       *, block_n, block_v, num_v_blocks, vocab_size):
    """Merged backward: ONE logits recompute per tile feeds both
    dh += p_adj @ E and dE += p_adjᵀ @ h — 3 N·V·D matmuls total
    (the scan path's backward cost) instead of the split kernels' 4.

    Grid (V/bv, N/bn), tokens innermost: dE accumulates in VMEM
    scratch across the inner sweep and writes once per vocab tile;
    dh accumulates ACROSS vocab tiles through an fp32 HBM buffer
    aliased input→output (read-modify-write per visit)."""
    nb = pl.program_id(1)
    vb = pl.program_id(0)

    @pl.when(nb == 0)
    def _init():
        de_scr[:] = jnp.zeros_like(de_scr)

    e = _masked_e(e_ref, vb, block_v, vocab_size)
    p = _p_adj(h_ref[:], e, t_ref, lse_ref, g_ref, vb, block_n, block_v,
               vocab_size)
    pc = p.astype(e.dtype)
    de_scr[:] += jax.lax.dot_general(
        pc, h_ref[:], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    contrib = jax.lax.dot_general(
        pc, e, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(vb == 0)
    def _first_visit():
        dh_out_ref[:] = contrib

    @pl.when(vb > 0)
    def _accumulate():
        dh_out_ref[:] = dh_in_ref[:] + contrib

    @pl.when(nb == pl.num_programs(1) - 1)
    def _finish():
        de_ref[:] = de_scr[:].astype(de_ref.dtype)


def _bwd_merged_b_kernel(h_ref, e_ref, t_ref, lse_ref, g_ref, de_in_ref,
                         dh_ref, de_out_ref, dh_scr,
                         *, block_n, block_v, num_v_blocks, vocab_size):
    """Merged backward, grid (N/bn, V/bv) with vocab innermost: dh
    accumulates in VMEM scratch (written once per token tile) and dE
    accumulates ACROSS token sweeps through the aliased HBM buffer.
    Per-sweep alias traffic is V·D (read+write) × N/bn sweeps — with
    bn ≥ 1024 that is less than variant A's N·D × V/bv, and the
    scratch-resident dh needs no roundtrips at all."""
    nb = pl.program_id(0)
    vb = pl.program_id(1)

    @pl.when(vb == 0)
    def _init():
        dh_scr[:] = jnp.zeros_like(dh_scr)

    e = _masked_e(e_ref, vb, block_v, vocab_size)
    p = _p_adj(h_ref[:], e, t_ref, lse_ref, g_ref, vb, block_n, block_v,
               vocab_size)
    pc = p.astype(e.dtype)
    dh_scr[:] += jax.lax.dot_general(
        pc, e, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    contrib = jax.lax.dot_general(
        pc, h_ref[:], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(nb == 0)
    def _first_sweep():
        de_out_ref[:] = contrib.astype(de_out_ref.dtype)

    @pl.when(nb > 0)
    def _accumulate():
        de_out_ref[:] = (de_in_ref[:].astype(jnp.float32)
                         + contrib).astype(de_out_ref.dtype)

    @pl.when(vb == num_v_blocks - 1)
    def _finish():
        dh_ref[:] = dh_scr[:].astype(dh_ref.dtype)


def _de_kernel(h_ref, e_ref, t_ref, lse_ref, g_ref, de_ref, acc_scr,
               *, block_n, block_v, num_v_blocks, vocab_size):
    """dE_v = Σ_i p_adj_iv h_i over token tiles; grid (V/bv, N/bn),
    tokens innermost."""
    nb = pl.program_id(1)
    vb = pl.program_id(0)

    @pl.when(nb == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    e = _masked_e(e_ref, vb, block_v, vocab_size)
    p = _p_adj(h_ref[:], e, t_ref, lse_ref, g_ref, vb, block_n, block_v,
               vocab_size)
    # (bv, bn) @ (bn, D)
    acc_scr[:] += jax.lax.dot_general(
        p.astype(h_ref.dtype), h_ref[:], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(nb == pl.num_programs(1) - 1)
    def _finish():
        de_ref[:] = acc_scr[:].astype(de_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call wrappers
# ---------------------------------------------------------------------------

def _pad_rows(x, multiple):
    n = x.shape[0]
    pad = (-n) % multiple
    if pad == 0:
        return x
    width = ((0, pad),) + ((0, 0),) * (x.ndim - 1)
    return jnp.pad(x, width)


def _pad_rows_fill(x, multiple, fill):
    n = x.shape[0]
    pad = (-n) % multiple
    if pad == 0:
        return x
    width = ((0, pad),) + ((0, 0),) * (x.ndim - 1)
    return jnp.pad(x, width, constant_values=fill)


def _fwd_call(h, emb, targets, block_n, block_v, interpret):
    n, d = h.shape
    v = emb.shape[0]
    nb, vb = pl.cdiv(n, block_n), pl.cdiv(v, block_v)
    lse, tl = pl.pallas_call(
        functools.partial(_fwd_kernel, block_n=block_n, block_v=block_v,
                          num_v_blocks=vb, vocab_size=v),
        grid=(nb, vb),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_v, d), lambda i, j: (j, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nb * block_n, 1), jnp.float32),
            jax.ShapeDtypeStruct((nb * block_n, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_n, 1), jnp.float32),
            pltpu.VMEM((block_n, 1), jnp.float32),
            pltpu.VMEM((block_n, 1), jnp.float32),
        ],
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )(_pad_rows(h, block_n), emb,
      # pad target rows with -1: matches no vocab column
      _pad_rows_fill(targets[:, None].astype(jnp.int32), block_n, -1))
    return lse[:n, 0], tl[:n, 0]


def _dh_call(h, emb, targets, lse, g, block_n, block_v, interpret):
    n, d = h.shape
    v = emb.shape[0]
    nb, vb = pl.cdiv(n, block_n), pl.cdiv(v, block_v)
    dh = pl.pallas_call(
        functools.partial(_dh_kernel, block_n=block_n, block_v=block_v,
                          num_v_blocks=vb, vocab_size=v),
        grid=(nb, vb),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_v, d), lambda i, j: (j, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((nb * block_n, d), h.dtype),
        scratch_shapes=[pltpu.VMEM((block_n, d), jnp.float32)],
        interpret=interpret,
    )(_pad_rows(h, block_n), emb,
      _pad_rows_fill(targets[:, None].astype(jnp.int32), block_n, -1),
      _pad_rows(lse[:, None], block_n), _pad_rows(g[:, None], block_n))
    return dh[:n]


def _bwd_merged_call(h, emb, targets, lse, g, block_n, block_v,
                     interpret):
    n, d = h.shape
    v = emb.shape[0]
    nb, vb = pl.cdiv(n, block_n), pl.cdiv(v, block_v)
    # Caller (_fused_ce_bwd) guarantees nb >= 4: the aliased dh buffer
    # is read back one vocab sweep after its write, and fewer inner
    # steps between them would race the write-back DMA.
    dh_init = jnp.zeros((nb * block_n, d), jnp.float32)
    dh, de = pl.pallas_call(
        functools.partial(_bwd_merged_kernel, block_n=block_n,
                          block_v=block_v, num_v_blocks=vb, vocab_size=v),
        grid=(vb, nb),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda j, i: (i, 0)),
            pl.BlockSpec((block_v, d), lambda j, i: (j, 0)),
            pl.BlockSpec((block_n, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((block_n, d), lambda j, i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_n, d), lambda j, i: (i, 0)),
            pl.BlockSpec((block_v, d), lambda j, i: (j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nb * block_n, d), jnp.float32),
            jax.ShapeDtypeStruct((vb * block_v, d), emb.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_v, d), jnp.float32)],
        input_output_aliases={5: 0},
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )(_pad_rows(h, block_n), emb,
      _pad_rows_fill(targets[:, None].astype(jnp.int32), block_n, -1),
      _pad_rows(lse[:, None], block_n),
      _pad_rows(g[:, None], block_n), dh_init)
    return dh[:n].astype(h.dtype), de[:v]


def _bwd_merged_b_call(h, emb, targets, lse, g, block_n, block_v,
                       interpret, de_acc_dtype=jnp.float32):
    n, d = h.shape
    v = emb.shape[0]
    nb, vb = pl.cdiv(n, block_n), pl.cdiv(v, block_v)
    # fp32 by default: the aliased dE accumulator round-trips HBM once
    # per token sweep, and bf16 would shed low-order gradient bits on
    # every sweep (then again at the cross-chunk sum).
    de_dtype = de_acc_dtype or emb.dtype
    de_init = jnp.zeros((vb * block_v, d), de_dtype)
    dh, de = pl.pallas_call(
        functools.partial(_bwd_merged_b_kernel, block_n=block_n,
                          block_v=block_v, num_v_blocks=vb, vocab_size=v),
        grid=(nb, vb),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_v, d), lambda i, j: (j, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_v, d), lambda i, j: (j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_v, d), lambda i, j: (j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nb * block_n, d), h.dtype),
            jax.ShapeDtypeStruct((vb * block_v, d), de_dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_n, d), jnp.float32)],
        input_output_aliases={5: 1},
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )(_pad_rows(h, block_n), emb,
      _pad_rows_fill(targets[:, None].astype(jnp.int32), block_n, -1),
      _pad_rows(lse[:, None], block_n),
      _pad_rows(g[:, None], block_n), de_init)
    return dh[:n], de[:v].astype(emb.dtype)


def _de_call(h, emb, targets, lse, g, block_n, block_v, interpret):
    n, d = h.shape
    v = emb.shape[0]
    nb, vb = pl.cdiv(n, block_n), pl.cdiv(v, block_v)
    de = pl.pallas_call(
        functools.partial(_de_kernel, block_n=block_n, block_v=block_v,
                          num_v_blocks=vb, vocab_size=v),
        grid=(vb, nb),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda j, i: (i, 0)),
            pl.BlockSpec((block_v, d), lambda j, i: (j, 0)),
            pl.BlockSpec((block_n, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda j, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_v, d), lambda j, i: (j, 0)),
        out_shape=jax.ShapeDtypeStruct((vb * block_v, d), emb.dtype),
        scratch_shapes=[pltpu.VMEM((block_v, d), jnp.float32)],
        interpret=interpret,
    )(_pad_rows(h, block_n), emb,
      _pad_rows_fill(targets[:, None].astype(jnp.int32), block_n, -1),
      _pad_rows(lse[:, None], block_n),
      # pad rows carry g=0 so they contribute nothing to dE
      _pad_rows(g[:, None], block_n))
    return de[:v]


# ---------------------------------------------------------------------------
# custom-VJP op
# ---------------------------------------------------------------------------

def _bwd_dispatch(hidden, embed, targets, lse, g, *, block_n, block_v,
                  interpret, variant, bwd_block_n, bwd_block_v):
    """Pick and run the backward kernels for one row chunk.

    Merged kernel: one logits recompute feeds both gradients (3
    N·V·D matmuls, the scan path's cost, vs the split kernels' 4).
    Variant "b" (dh in scratch, dE through the aliased buffer) has the
    lower accumulation traffic when N/bn sweeps are few; variant "a"
    (roles swapped) kept for sweeping; variant "split" forces the
    race-free unmerged kernels. Backward tiles derive from the
    caller's forward tiles (wider rows, narrower vocab — the fp32
    accumulators dominate VMEM) unless overridden explicitly.
    """
    if interpret:
        # The merged kernel accumulates dh through an input→output
        # ALIASED buffer — a compiled-mode memory property the
        # interpreter does not emulate (inputs there are functional
        # copies), so interpret mode runs the split kernels instead.
        variant = "split"
    n, v = hidden.shape[0], embed.shape[0]
    bn = min(bwd_block_n if bwd_block_n else min(2 * block_n, 1024), n)
    bv = min(bwd_block_v if bwd_block_v else max(128, block_v // 4), v)
    nb, vb = pl.cdiv(n, bn), pl.cdiv(v, bv)
    # The aliased accumulator block is re-read one sweep after its
    # write; with < 4 grid steps between them the write-back DMA may
    # not have landed before the prefetch (stale read). Variant A's
    # gap is nb steps, variant B's is vb — fall back to the split
    # kernels (no aliasing at all) when the margin is too thin.
    if variant == "a" and nb >= 4:
        return _bwd_merged_call(hidden, embed, targets, lse, g,
                                bn, bv, interpret)
    if variant == "b" and vb >= 4:
        return _bwd_merged_b_call(hidden, embed, targets, lse, g,
                                  bn, bv, interpret)
    dh = _dh_call(hidden, embed, targets, lse, g, block_n, block_v,
                  interpret)
    de = _de_call(hidden, embed, targets, lse, g, block_n,
                  min(block_v, 512), interpret)
    return dh, de


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _fused_ce(hidden, embed, targets, block_n, block_v, interpret,
              variant, bwd_block_n, bwd_block_v):
    losses, _ = _fused_ce_fwd(hidden, embed, targets, block_n, block_v,
                              interpret, variant, bwd_block_n, bwd_block_v)
    return losses


def _fused_ce_fwd(hidden, embed, targets, block_n, block_v, interpret,
                  variant, bwd_block_n, bwd_block_v):
    lse, tl = _fwd_call(hidden, embed, targets, block_n, block_v,
                        interpret)
    return lse - tl, (hidden, embed, targets, lse)


def _fused_ce_bwd(block_n, block_v, interpret, variant, bwd_block_n,
                  bwd_block_v, res, g):
    hidden, embed, targets, lse = res
    g = g.astype(jnp.float32)
    dh, de = _bwd_dispatch(hidden, embed, targets, lse, g,
                           block_n=block_n, block_v=block_v,
                           interpret=interpret, variant=variant,
                           bwd_block_n=bwd_block_n,
                           bwd_block_v=bwd_block_v)
    return dh, de, None


_fused_ce.defvjp(_fused_ce_fwd, _fused_ce_bwd)


def fused_cross_entropy(hidden, embed, targets, *,
                        block_n: int = 512, block_v: int = 1024,
                        implementation: str | None = None,
                        bwd_variant: str = "b",
                        bwd_block_n: int | None = None,
                        bwd_block_v: int | None = None):
    """Per-token CE losses of ``hidden @ embed.T`` against ``targets``
    without materializing the (N, V) logits.

    hidden: (N, D) activations (bf16/fp32); embed: (V, D) tied embedding
    in the SAME dtype (cast outside, as the scan path does); targets:
    (N,) int. Returns fp32 (N,) losses; differentiable wrt hidden/embed.

    implementation: "pallas" | "reference" | "interpret" | None
    (auto: pallas on TPU, reference elsewhere).

    bwd_variant: "b" | "a" | "split" — merged-backward flavor (see
    ``_bwd_dispatch``); explicit kwargs, not env vars, so every process
    in a multi-host job traces the same program.
    """
    if implementation is None:
        implementation = ("pallas" if jax.default_backend() == "tpu"
                          else "reference")
    if implementation == "reference":
        return ce_reference(hidden, embed, targets)
    n, v = hidden.shape[0], embed.shape[0]
    interp = implementation == "interpret"
    # Row-chunking bounds the merged backward's aliased-dE traffic
    # (N/bn sweeps × V·D read+write per chunk) and keeps every chunk in
    # the VMEM-validated batch-4 tile geometry; autodiff sums the
    # per-chunk dE cotangents into the embedding gradient for free.
    row_chunk = 4096
    if n <= row_chunk or n % row_chunk:
        return _fused_ce(hidden, embed, targets, min(block_n, n),
                         min(block_v, v), interp, bwd_variant,
                         bwd_block_n, bwd_block_v)
    return jnp.concatenate([
        _fused_ce(hidden[i:i + row_chunk], embed,
                  targets[i:i + row_chunk], block_n,
                  min(block_v, v), interp, bwd_variant,
                  bwd_block_n, bwd_block_v)
        for i in range(0, n, row_chunk)])


# ---------------------------------------------------------------------------
# Sharded op: shard_map over token axes, two-pass merge over a tp vocab
# ---------------------------------------------------------------------------

def _local_targets(t, e_rows, vocab_axis):
    """Map global target ids to this vocab shard's local row space; ids
    owned by another shard become -1 (matches no column, so they add 0
    to the local target-logit partial and the one-hot correction)."""
    if vocab_axis is None:
        return t
    off = jax.lax.axis_index(vocab_axis) * e_rows
    return jnp.where((t >= off) & (t < off + e_rows), t - off, -1)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11))
def _sharded_ce(hidden, embed, targets, mesh, batch_axes, seq_axis,
                vocab_axis, block_n, block_v, interpret, variant,
                bwd_blocks):
    losses, _ = _sharded_ce_fwd(hidden, embed, targets, mesh, batch_axes,
                                seq_axis, vocab_axis, block_n, block_v,
                                interpret, variant, bwd_blocks)
    return losses


def _token_specs(batch_axes, seq_axis):
    from jax.sharding import PartitionSpec as P
    b = batch_axes if batch_axes else None
    return P(b, seq_axis), P(b, seq_axis, None)


def _sharded_ce_fwd(hidden, embed, targets, mesh, batch_axes, seq_axis,
                    vocab_axis, block_n, block_v, interpret, variant,
                    bwd_blocks):
    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    tspec, hspec = _token_specs(batch_axes, seq_axis)

    def body(h, e, t):
        bl, sl, d = h.shape
        n = bl * sl
        hf, tf = h.reshape(n, d), t.reshape(n)
        tf = _local_targets(tf, e.shape[0], vocab_axis)
        lse, tl = _fwd_call(hf, e, tf, min(block_n, n),
                            min(block_v, e.shape[0]), interpret)
        if vocab_axis is not None:
            # Cross-shard logsumexp merge: each shard holds the online
            # (running-max form) logsumexp of ITS vocab slice; combine
            # exactly, then sum the (one-owner) target-logit partials.
            m = jax.lax.pmax(lse, vocab_axis)
            lse = m + jnp.log(jax.lax.psum(jnp.exp(lse - m), vocab_axis))
            tl = jax.lax.psum(tl, vocab_axis)
        return ((lse - tl).reshape(bl, sl), lse.reshape(bl, sl))

    losses, lse = shard_map(
        body, mesh=mesh,
        in_specs=(hspec, P(vocab_axis, None), tspec),
        out_specs=(tspec, tspec), check_vma=False)(hidden, embed, targets)
    return losses, (hidden, embed, targets, lse)


def _sharded_ce_bwd(mesh, batch_axes, seq_axis, vocab_axis, block_n,
                    block_v, interpret, variant, bwd_blocks, res, g):
    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    hidden, embed, targets, lse = res
    tspec, hspec = _token_specs(batch_axes, seq_axis)
    token_axes = tuple(batch_axes) + ((seq_axis,) if seq_axis else ())
    bwd_block_n, bwd_block_v = bwd_blocks

    def body(h, e, t, lse_l, g_l):
        bl, sl, d = h.shape
        n = bl * sl
        hf, tf = h.reshape(n, d), t.reshape(n)
        tf = _local_targets(tf, e.shape[0], vocab_axis)
        row_chunk = 4096
        step = row_chunk if (n > row_chunk and n % row_chunk == 0) else n
        dhs, de = [], None
        for i in range(0, n, step):
            dh_c, de_c = _bwd_dispatch(
                hf[i:i + step], e, tf[i:i + step],
                lse_l.reshape(n)[i:i + step],
                g_l.reshape(n)[i:i + step].astype(jnp.float32),
                block_n=min(block_n, step), block_v=min(block_v, e.shape[0]),
                interpret=interpret, variant=variant,
                bwd_block_n=bwd_block_n, bwd_block_v=bwd_block_v)
            dhs.append(dh_c)
            de = de_c if de is None else de + de_c.astype(jnp.float32)
        dh = jnp.concatenate(dhs) if len(dhs) > 1 else dhs[0]
        if vocab_axis is not None:
            # Each vocab shard produced dh from ITS vocab slice only.
            dh = jax.lax.psum(dh.astype(jnp.float32), vocab_axis)
        if token_axes:
            # Each token shard produced dE from ITS tokens only.
            de = jax.lax.psum(de.astype(jnp.float32), token_axes)
        return (dh.astype(h.dtype).reshape(bl, sl, d),
                de.astype(e.dtype))

    dh, de = shard_map(
        body, mesh=mesh,
        in_specs=(hspec, P(vocab_axis, None), tspec, tspec, tspec),
        out_specs=(hspec, P(vocab_axis, None)), check_vma=False)(
            hidden, embed, targets, lse, g)
    return dh, de, None


_sharded_ce.defvjp(_sharded_ce_fwd, _sharded_ce_bwd)


def sharded_fused_cross_entropy(hidden, embed, targets, mesh, *,
                                block_n: int = 512, block_v: int = 1024,
                                implementation: str | None = None,
                                bwd_variant: str = "b",
                                bwd_block_n: int | None = None,
                                bwd_block_v: int | None = None):
    """``fused_cross_entropy`` for sharded meshes: the kernels run
    per-shard under ``shard_map`` (Pallas custom calls cannot be GSPMD-
    partitioned — same constraint as ops/attention.py
    ``sharded_flash_attention``), with tokens sharded over the mesh's
    data axes (dcn/dp/fsdp) and the sequence axis (sp), and the vocab
    either replicated or sharded over tp.

    Layouts and collectives (all forward-only, inside custom_vjp):
    - dp/fsdp/sp: embarrassingly parallel over tokens; the backward
      psums dE over the token axes (each shard saw only its tokens).
    - tp (vocab-sharded embedding): two-pass merge — each shard's
      forward kernel produces the logsumexp of its vocab slice and a
      target-logit partial; an exact ``pmax``/``psum`` combine yields
      the global row logsumexp, which the backward feeds to each
      shard's probability recompute, psumming dh over tp.

    hidden: (B, S, D) global array; targets: (B, S) int; returns (B, S)
    fp32 losses. ≙ the reference's fused softmax-CE partitioning under
    every strategy (TF/python/ops/nn_ops.py
    softmax_cross_entropy_with_logits — a fused XLA reduction GSPMD
    partitions like any HLO; here the partitioning is explicit because
    the op is a Mosaic custom call).
    """
    if implementation is None:
        implementation = ("pallas" if jax.default_backend() == "tpu"
                          else "reference")
    if implementation == "reference":
        B, S, D = hidden.shape
        return ce_reference(hidden.reshape(B * S, D), embed,
                            targets.reshape(B * S)).reshape(B, S)

    def axis_used(a):
        return a in mesh.shape and mesh.shape[a] > 1

    batch_axes = tuple(a for a in ("dcn", "dp", "fsdp") if axis_used(a))
    seq_axis = "sp" if axis_used("sp") else None
    vocab_axis = "tp" if axis_used("tp") else None
    B, S, _ = hidden.shape
    n_batch = 1
    for a in batch_axes:
        n_batch *= mesh.shape[a]
    if (B % n_batch or (seq_axis and S % mesh.shape[seq_axis])
            or (vocab_axis and embed.shape[0] % mesh.shape[vocab_axis])):
        raise ValueError(
            f"sharded_fused_cross_entropy: shapes B={B}, S={S}, "
            f"V={embed.shape[0]} not divisible by mesh shards "
            f"{dict(mesh.shape)}")
    return _sharded_ce(hidden, embed, targets, mesh, batch_axes, seq_axis,
                       vocab_axis, block_n, block_v,
                       implementation == "interpret", bwd_variant,
                       (bwd_block_n, bwd_block_v))
