"""Compute ops: pallas kernels, attention, embeddings, optim utilities."""

from distributed_tensorflow_tpu.parallel import collectives as collective_ops  # re-export
from distributed_tensorflow_tpu.ops.attention import (  # noqa: F401
    flash_attention, mha_reference)
