"""Fused AdamW update for TPU (Pallas, aliased in-place buffers).

The optimizer update is the bandwidth-bound tail of the train step: for
the flagship transformer (235M fp32 params) the information floor is
read {p, g, mu, nu} + write {p, mu, nu} = 28 B/param ≈ 6.6 GB, ~8 ms at
v5e HBM bandwidth — but the XLA lowering of the optax chain measures
~14 ms (≈13% of the step): the (updates, new_state) functional shape of
``scale_by_adam`` → ``add_decayed_weights`` → ``scale`` materializes
intermediate trees that fusion does not fully collapse. This kernel does
the whole read-modify-write in ONE pass per parameter block, with every
output aliased onto its input buffer (true in-place update, no second
allocation), which pins the traffic at the floor.

≙ the reference's fused training ops (TF/python/training/training_ops.py
``resource_apply_adam`` — a single fused C++/CUDA kernel mutating the
variable and slots in place; the functional-JAX equivalent of "mutate in
place" is input→output aliasing + donation).

Semantics match ``optax.adamw`` exactly (same bias correction, eps
placement outside the sqrt, decoupled weight decay, update order):
    mu'  = b1·mu + (1-b1)·g
    nu'  = b2·nu + (1-b2)·g²
    u    = (mu'/(1-b1^t)) / (sqrt(nu'/(1-b2^t)) + eps) + wd·p
    p'   = p - lr·u
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Elementwise tiles: (rows, 1024) fp32. 256×1024×4B = 1 MiB per operand
# block; 4 in + 3 aliased out keep VMEM well under the 16 MiB default.
_LANES = 1024
_ROWS = 256


def adamw_reference(p, g, mu, nu, c1, c2, *, lr, b1, b2, eps, wd):
    """Plain-jnp contract (and non-TPU fallback); c1 = 1/(1-b1^t),
    c2 = 1/(1-b2^t) are the (dynamic) bias corrections."""
    p32, g32 = p.astype(jnp.float32), g.astype(jnp.float32)
    mu2 = b1 * mu.astype(jnp.float32) + (1.0 - b1) * g32
    nu2 = b2 * nu.astype(jnp.float32) + (1.0 - b2) * jnp.square(g32)
    u = (mu2 * c1) / (jnp.sqrt(nu2 * c2) + eps) + wd * p32
    return ((p32 - lr * u).astype(p.dtype), mu2.astype(mu.dtype),
            nu2.astype(nu.dtype))


def _adamw_kernel(c_ref, p_ref, g_ref, mu_ref, nu_ref,
                  po_ref, muo_ref, nuo_ref, *, lr, b1, b2, eps, wd):
    c1 = c_ref[0]
    c2 = c_ref[1]
    g = g_ref[:].astype(jnp.float32)
    mu2 = b1 * mu_ref[:].astype(jnp.float32) + (1.0 - b1) * g
    nu2 = b2 * nu_ref[:].astype(jnp.float32) + (1.0 - b2) * g * g
    p = p_ref[:].astype(jnp.float32)
    u = (mu2 * c1) / (jnp.sqrt(nu2 * c2) + eps) + wd * p
    po_ref[:] = (p - lr * u).astype(po_ref.dtype)
    muo_ref[:] = mu2.astype(muo_ref.dtype)
    nuo_ref[:] = nu2.astype(nuo_ref.dtype)


def _fused_leaf_update(p, g, mu, nu, corrections, *, lr, b1, b2, eps, wd,
                       interpret):
    """One parameter leaf in one aliased pallas pass. The three outputs
    alias their input buffers — with jit donation this is a true
    in-place update.

    Layout discipline: a leaf that is already (..., cols) with a
    128-multiple minor dim is viewed as (prod(leading), cols) — under
    TPU tiling that collapse is physically free, whereas flattening to
    a fixed (N/1024, 1024) grid re-tiles the buffer (a full extra
    read+write per operand, which is how the first version of this
    kernel LOST to XLA's fusions). Only oddly-shaped small leaves
    (biases, norm scales) take the pad-and-reshape path."""
    shape = p.shape
    n = p.size
    if p.ndim >= 2 and shape[-1] % 128 == 0:
        cols = shape[-1]
        rows_total = n // cols
    else:
        cols = _LANES if n >= _LANES else max(
            128, 1 << (n - 1).bit_length())
        rows_total = -(-n // cols)
    block_rows = min(max(_ROWS // max(cols // _LANES, 1), 8), rows_total)

    def prep(x):
        if x.ndim >= 2 and x.shape[-1] % 128 == 0:
            return x.reshape(-1, x.shape[-1])
        flat = x.reshape(-1)
        pad = rows_total * cols - n
        if pad:
            flat = jnp.pad(flat, (0, pad))
        return flat.reshape(rows_total, cols)

    grid = (pl.cdiv(rows_total, block_rows),)

    def spec_for(dtype):
        return pl.BlockSpec((block_rows, cols), lambda i: (i, 0))

    p2, mu2, nu2 = pl.pallas_call(
        functools.partial(_adamw_kernel, lr=lr, b1=b1, b2=b2, eps=eps,
                          wd=wd),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            spec_for(p.dtype), spec_for(g.dtype),
            spec_for(mu.dtype), spec_for(nu.dtype),
        ],
        out_specs=[spec_for(p.dtype), spec_for(mu.dtype),
                   spec_for(nu.dtype)],
        out_shape=[
            jax.ShapeDtypeStruct((rows_total, cols), p.dtype),
            jax.ShapeDtypeStruct((rows_total, cols), mu.dtype),
            jax.ShapeDtypeStruct((rows_total, cols), nu.dtype),
        ],
        # operands: 0=corrections(SMEM), 1=p, 2=g, 3=mu, 4=nu
        input_output_aliases={1: 0, 3: 1, 4: 2},
        interpret=interpret,
    )(corrections, prep(p), prep(g), prep(mu), prep(nu))

    def unprep(x):
        return x.reshape(-1)[:n].reshape(shape)

    return unprep(p2), unprep(mu2), unprep(nu2)


def fused_adamw_update(params, grads, mu, nu, count, *,
                       lr: float, b1: float = 0.9, b2: float = 0.999,
                       eps: float = 1e-8, weight_decay: float = 0.0,
                       implementation: str | None = None,
                       mesh=None, param_specs=None):
    """Apply one AdamW step to a whole pytree in fused one-pass kernels.

    params/grads/mu/nu: matching pytrees; count: the PRE-increment step
    counter (optax convention: bias corrections use count+1). Returns
    (new_params, new_mu, new_nu, new_count).

    implementation: "pallas" | "interpret" | "reference" | None (auto:
    pallas on TPU, reference elsewhere). With ``mesh`` + ``param_specs``
    (a pytree of PartitionSpecs matching params' structure) each leaf's
    kernel runs per-shard under shard_map — the update is elementwise,
    so any sharding layout is valid and no collectives are needed.
    """
    if implementation is None:
        implementation = ("pallas" if jax.default_backend() == "tpu"
                          else "reference")
    new_count = count + 1
    cf = new_count.astype(jnp.float32)
    c1 = 1.0 / (1.0 - jnp.power(b1, cf))
    c2 = 1.0 / (1.0 - jnp.power(b2, cf))

    leaves_p, treedef = jax.tree_util.tree_flatten(params)
    leaves_g = treedef.flatten_up_to(grads)
    leaves_m = treedef.flatten_up_to(mu)
    leaves_v = treedef.flatten_up_to(nu)

    if implementation == "reference":
        out = [adamw_reference(p, g, m, v, c1, c2, lr=lr, b1=b1, b2=b2,
                               eps=eps, wd=weight_decay)
               for p, g, m, v in zip(leaves_p, leaves_g, leaves_m,
                                     leaves_v)]
    else:
        interp = implementation == "interpret"
        corrections = jnp.stack([c1, c2])
        leaf_fn = functools.partial(_fused_leaf_update, lr=lr, b1=b1,
                                    b2=b2, eps=eps, wd=weight_decay,
                                    interpret=interp)
        sharded = (mesh is not None and mesh.size > 1
                   and param_specs is not None)
        if sharded:
            from jax import shard_map
            from jax.sharding import PartitionSpec as P
            leaves_s = jax.tree_util.tree_leaves(
                param_specs, is_leaf=lambda x: isinstance(x, P))
            if len(leaves_s) != len(leaves_p):
                raise ValueError(
                    f"param_specs has {len(leaves_s)} specs for "
                    f"{len(leaves_p)} parameter leaves")
            out = []
            for p, g, m, v, s in zip(leaves_p, leaves_g, leaves_m,
                                     leaves_v, leaves_s):
                out.append(shard_map(
                    leaf_fn, mesh=mesh, in_specs=(s, s, s, s, P()),
                    out_specs=(s, s, s), check_vma=False)(
                        p, g, m, v, corrections))
        else:
            out = [leaf_fn(p, g, m, v, corrections)
                   for p, g, m, v in zip(leaves_p, leaves_g, leaves_m,
                                         leaves_v)]

    unflat = lambda i: jax.tree_util.tree_unflatten(
        treedef, [t[i] for t in out])
    return unflat(0), unflat(1), unflat(2), new_count
