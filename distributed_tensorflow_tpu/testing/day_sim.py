"""Compressed production-day scenario over the simulated shared fleet.

The macro-scenario the per-subsystem sweeps cannot produce: ONE
supervisor-run fleet of serving replicas + elastic trainers driven
through a diurnal curve — night-rate serving over a batch-training
backfill, a morning interactive ramp (one trainer's capacity donated to
the day via the real ``request_scale`` path), peak, a flash spike past
fleet capacity, a whole-RACK loss at peak (``SimRunner.
terminate_domain`` — the correlated failure the placement policy
exists for), and the night-2 drain. Everything is the production code
under test:

- the real :class:`~distributed_tensorflow_tpu.resilience.supervisor.
  RecoverySupervisor` watches/reforms (thread-backed :class:`~
  distributed_tensorflow_tpu.testing.fleet_sim.SimRunner` underneath,
  with a :class:`~distributed_tensorflow_tpu.testing.fleet_sim.
  DomainTopology` placing workers into racks);
- trainers snapshot + ring-replicate through the real
  ``checkpoint/peer_snapshot`` exchange — domain-spread
  (``assign_replicators`` with the rack map) or deliberately blind
  (``domain_spread=False``), which is how the warm-tier regression is
  demonstrated: a 2-trainer rack kill under the blind ring takes an
  owner AND its only replica, forcing a durable (cold) restore;
- every worker logs real telemetry events; the day is scored
  afterwards, purely from those logs, by ``telemetry/audit.py``.

Serving is queue-true rather than model-true: the driver generates
arrivals into one shared fleet queue; replicas admit up to their
capacity per tick and log each completion's true queueing delay + service
time as ``serve.request``. Load above fleet capacity (the spike) or a
reform outage (the rack loss: the WHOLE generation respawns) therefore
produces honest latency-tail violations at honest instants — which is
exactly what the audit's cause attribution is graded against. Admitted
requests are never dropped: the queue outlives worker incarnations and
a cooperative kill cannot interrupt the pop→log critical section.
"""

from __future__ import annotations

import collections
import dataclasses
import random
import tempfile
import threading
import time

import numpy as np

from distributed_tensorflow_tpu.checkpoint import peer_snapshot as ps
from distributed_tensorflow_tpu.cluster import coordination, elastic
from distributed_tensorflow_tpu.resilience.retry import RetryPolicy
from distributed_tensorflow_tpu.resilience.supervisor import (
    RecoverySupervisor,
)
from distributed_tensorflow_tpu.telemetry import events as tv_events
from distributed_tensorflow_tpu.testing import fleet_sim


@dataclasses.dataclass(frozen=True)
class DayPhase:
    """One segment of the diurnal curve."""

    name: str
    dur_s: float
    rate_rps: float
    #: elastic resize fired at phase start (None = keep)
    scale_to: "int | None" = None
    #: the seeded whole-rack kill lands inside this phase
    rack_kill: bool = False


def default_phases(*, compress: float = 1.0) -> "tuple[DayPhase, ...]":
    """The compressed day: ~6s of wall at ``compress=1``. Rates are
    sized against the default fleet's ~600 req/s serving capacity
    (4 replicas x 3/tick / 0.02s): the spike is the only segment past
    capacity, the rack loss rides peak-rate load."""
    c = compress
    return (
        DayPhase("night", 0.8 * c, 40.0),
        DayPhase("ramp", 0.8 * c, 150.0, scale_to=7),
        DayPhase("peak", 0.8 * c, 250.0),
        DayPhase("spike", 0.5 * c, 1400.0),
        # a second peak segment separates the spike's queue drain from
        # the rack kill, so the audit's two loudest causes
        # (spike_overload, recovery) are observably distinct
        DayPhase("peak_2", 1.2 * c, 250.0),
        DayPhase("rack_loss", 1.6 * c, 250.0, rack_kill=True),
        DayPhase("night_2", 0.8 * c, 40.0),
    )


class _PeerAgent(fleet_sim.SimAgent):
    """SimAgent that reports ``is_distributed`` from its simulated
    world size: the base class pins ``_client`` to None (every op takes
    the in-process service path), which ``CoordinationServiceAgent.
    is_distributed`` reads as single-process — correct for the fleet
    harness's own collectives but wrong here, where the trainer
    sub-world must run the REAL peer-snapshot exchange/negotiate
    collectives (both no-op on non-distributed agents)."""

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1


class DaySim:
    """One seeded compressed production day; ``run()`` returns the
    driver-side report, and ``logdir`` afterwards holds everything
    ``telemetry/audit.audit_day`` needs to score it.

    ``domain_spread=False`` keeps the fleet topology (and the
    correlated rack kill) but reverts the peer-snapshot ring to the
    blind ``(pid - 1) % N`` placement — the acceptance-criteria
    negative: the rack kill then takes an owner and its replica
    together and the restore falls through to the durable tier.
    """

    def __init__(self, *, seed: int = 0, logdir: "str | None" = None,
                 domain_spread: bool = True,
                 num_servers: int = 4, num_trainers: int = 4,
                 workers_per_domain: int = 2,
                 phases: "tuple | list | None" = None,
                 serve_tick_s: float = 0.02, server_capacity: int = 3,
                 service_s: float = 0.04,
                 train_step_s: float = 0.04, snap_every: int = 4,
                 exchange_timeout_s: float = 2.0,
                 max_restarts: int = 4,
                 drain_timeout_s: float = 15.0,
                 two_tenant: bool = False,
                 batch_frac: float = 0.25):
        if num_servers < 1 or num_trainers < 2:
            raise ValueError("need >=1 server and >=2 trainers")
        self.seed = seed
        self.logdir = logdir or tempfile.mkdtemp(prefix="day_sim_tel_")
        self.domain_spread = domain_spread
        self.num_servers = num_servers
        self.num_trainers = num_trainers
        self.workers_per_domain = workers_per_domain
        self.phases = tuple(phases) if phases is not None \
            else default_phases()
        self.serve_tick_s = serve_tick_s
        self.server_capacity = server_capacity
        self.service_s = service_s
        self.train_step_s = train_step_s
        self.snap_every = snap_every
        self.exchange_timeout_s = exchange_timeout_s
        self.max_restarts = max_restarts
        self.drain_timeout_s = drain_timeout_s
        self.kv = coordination._LocalService()
        self.topology = fleet_sim.DomainTopology(
            num_servers + num_trainers,
            workers_per_domain=workers_per_domain)
        self._runner: "fleet_sim.SimRunner | None" = None
        self._day_over = threading.Event()
        #: optional two-tenant serving stream (ISSUE 20): a seeded
        #: ``batch_frac`` share of arrivals belongs to the batch tenant
        #: and admits AFTER interactive each tick — the router
        #: frontend's batch-sheds-first policy on the diurnal curve.
        #: Batch therefore only queues behind interactive inside the
        #: already-attributed overload/recovery windows, so the audit's
        #: unattributed gate still holds.
        self.two_tenant = two_tenant
        self.batch_frac = batch_frac
        self._tenant_rng = random.Random(f"day-tenants:{seed}")
        #: the shared fleet admission queues: arrival wall stamps.
        #: Owned by the sim (not any worker incarnation), so a reform
        #: parks the backlog instead of dropping it. ``_queue_batch``
        #: stays empty unless ``two_tenant``.
        self._queue: "collections.deque[float]" = collections.deque()
        self._queue_batch: "collections.deque[float]" = \
            collections.deque()
        self._q_lock = threading.Lock()
        self._generated = 0
        self._completed = 0
        self._completed_batch = 0
        self._done_lock = threading.Lock()
        self._phase_name = "pre"

    # -- worker side ------------------------------------------------------
    def _worker_main(self, ctx: fleet_sim.SimTaskContext):
        gen = ctx.generation
        with elastic.generation_override(gen):
            log = tv_events.EventLog(
                tv_events.event_log_path(self.logdir, ctx.pid),
                process_id=ctx.pid)
            try:
                if ctx.pid < self.num_servers:
                    return self._server_loop(ctx, log)
                return self._trainer_loop(ctx, log)
            finally:
                log.close()

    def _server_loop(self, ctx, log):
        while not self._day_over.is_set():
            ctx.check_kill()
            tick_start = time.time()
            with self._q_lock:
                # interactive admits first; batch takes whatever
                # capacity is left this tick (the two-tenant day's
                # shed-first policy — a no-op pop when single-tenant)
                popped = [(self._queue.popleft(), "interactive")
                          for _ in range(min(self.server_capacity,
                                             len(self._queue)))]
                popped += [(self._queue_batch.popleft(), "batch")
                           for _ in range(
                               min(self.server_capacity - len(popped),
                                   len(self._queue_batch)))]
            now = time.time()
            n_batch = 0
            for arrival, kind in popped:
                # queueing delay + deterministic service time = the
                # honest completion latency; logged atomically with the
                # pop, so an admitted request is never lost to a kill
                lat = max(0.0, now - arrival) + self.service_s
                stamp = {}
                if self.two_tenant:
                    stamp["tenant"] = ("batchco" if kind == "batch"
                                       else "acme")
                    stamp["pclass"] = kind
                    n_batch += kind == "batch"
                log.event("serve.request", kind=kind,
                          dur_s=round(lat, 6),
                          ttft_s=round(0.5 * lat, 6),
                          new_tokens=32, replayed_tokens=0,
                          model_version="v1", error=False,
                          phase=self._phase_name, **stamp)
            with self._done_lock:
                self._completed += len(popped)
                self._completed_batch += n_batch
            ctx.sleep(self.serve_tick_s)
            log.event("serve.step",
                      dur_s=round(time.time() - tick_start, 6),
                      admitted=len(popped), phase=self._phase_name)
        return ctx.pid

    def _trainer_domains(self, world: int) -> "dict[int, str] | None":
        """Trainer-local {idx: rack} from the deterministic block
        placement (every incarnation recomputes the identical map — no
        coordination needed), or None when running the blind ring."""
        if not self.domain_spread:
            return None
        topo = fleet_sim.DomainTopology(
            self.num_servers + world,
            workers_per_domain=self.workers_per_domain)
        return {i: topo.domain_of(self.num_servers + i)
                for i in range(world)}

    def _trainer_loop(self, ctx, log):
        t_idx = ctx.pid - self.num_servers
        world = ctx.num_workers - self.num_servers
        agent = _PeerAgent(self.kv, t_idx, world)
        domains = self._trainer_domains(world)
        memdir = elastic.peer_memdir_path(
            ctx.env[elastic.ENV_SUPERVISOR_DIR], ctx.pid)
        store = ps.SnapshotStore(memdir, keep=2)
        store.load_surviving()
        step = 0
        if ctx.generation > 0:
            # collective restore decision for the reformed generation;
            # the cold durable fallback stands in for the real job's
            # disk checkpoint at step 0
            decision = ps.negotiate(
                store, agent, disk_best=(0, "cold://day-seed",
                                         "durable"),
                timeout_s=self.exchange_timeout_s * 4)
            if decision["source"] == "memory":
                ps.fetch_parts(store, agent, decision,
                               timeout_s=self.exchange_timeout_s * 4)
                tier = ("peer" if ps.any_fetched_remotely(store,
                                                          decision)
                        else "host")
                step = int(decision["step"])
            elif decision["source"] == "disk":
                tier = decision.get("tier", "durable")
                step = int(decision.get("step", 0))
            else:
                tier = "none"
            log.event("recovery.restore_tier", tier=tier, step=step,
                      source=decision["source"], t_idx=t_idx,
                      domain=ctx.domain)
        while not self._day_over.is_set():
            ctx.check_kill()
            t0 = time.time()
            ctx.sleep(self.train_step_s)
            step += 1
            log.event("train.step", step=step,
                      dur_s=round(time.time() - t0, 6),
                      phase=self._phase_name)
            if step % self.snap_every == 0:
                snap = ps.HostSnapshot(
                    owner=t_idx, step=step, world=world,
                    index={"day": True},
                    arrays={"w": np.full(4, float(step))})
                store.put(snap)
                ps.exchange(store, snap, agent,
                            timeout_s=self.exchange_timeout_s,
                            domains=domains)
        return ctx.pid

    # -- supervisor plumbing (the FleetSim injection pattern) -------------
    def _agent(self, pid: int, n: int) -> fleet_sim.SimAgent:
        return fleet_sim.SimAgent(self.kv, pid, n)

    def _runner_factory(self, fn, spec, **kw):
        kw.pop("agent_factory", None)
        self._runner = fleet_sim.SimRunner(
            fn, spec, agent_factory=self._agent,
            topology=self.topology, **kw)
        return self._runner

    # -- the day ----------------------------------------------------------
    def _eligible_racks(self) -> "list[str]":
        """Full trainer racks — the correlated-loss demo targets a rack
        whose loss removes BOTH of a (blind) owner/replicator pair."""
        topo = self._runner.topology
        out = []
        for d in topo.domains:
            members = topo.members(d)
            if members and min(members) >= self.num_servers and \
                    len(members) >= 2:
                out.append(d)
        return out

    def run(self) -> dict:
        n0 = self.num_servers + self.num_trainers
        work_dir = tempfile.mkdtemp(prefix="day_sim_work_")
        supervisor = RecoverySupervisor(
            self._worker_main, num_workers=n0,
            max_restarts=self.max_restarts,
            retry_policy=RetryPolicy(
                max_attempts=self.max_restarts + 1,
                initial_backoff_s=0.02, backoff_multiplier=1.5,
                max_backoff_s=0.2),
            stall_timeout_s=None,          # no heartbeats in this sim
            generation_timeout_s=300.0,
            poll_interval_s=0.02,
            telemetry_dir=self.logdir, work_dir=work_dir,
            min_workers=self.num_servers + 2,
            runner_factory=self._runner_factory,
            cluster_spec_fn=fleet_sim.sim_cluster_spec)
        supervisor._start_exporter = lambda: None
        outcome: dict = {}

        def _drive():
            try:
                outcome["result"] = supervisor.run()
            except BaseException as e:      # noqa: BLE001
                outcome["error"] = e

        driver = tv_events.EventLog(
            tv_events.event_log_path(self.logdir, "driver"),
            process_id="driver")
        driver.event("day.topology", seed=self.seed,
                     domain_spread=self.domain_spread,
                     num_servers=self.num_servers,
                     num_trainers=self.num_trainers,
                     domains={str(p): d for p, d in
                              self.topology.as_map().items()})
        kill_fired: "dict | None" = None
        t0 = time.time()
        sup_thread = threading.Thread(target=_drive, daemon=True,
                                      name="day-supervisor")
        sup_thread.start()
        try:
            for phase in self.phases:
                self._phase_name = phase.name
                driver.event("day.phase", phase=phase.name,
                             rate_rps=phase.rate_rps,
                             dur_s=phase.dur_s)
                if phase.scale_to is not None:
                    supervisor.request_scale(
                        phase.scale_to, reason=f"day_{phase.name}")
                kill_at = None
                if phase.rack_kill and self._runner is not None:
                    racks = self._eligible_racks()
                    plan = fleet_sim.seeded_domain_kill_plan(
                        self.seed, self._runner.topology, kills=1,
                        after_range=(0.25, 0.6),
                        eligible=racks or None)
                    if plan:
                        kill_at = (time.monotonic() + plan[0].after_s,
                                   plan[0].domain)
                deadline = time.monotonic() + phase.dur_s
                carry = 0.0
                last = time.monotonic()
                while time.monotonic() < deadline:
                    if not sup_thread.is_alive():
                        raise RuntimeError(
                            f"supervisor died mid-day: "
                            f"{outcome.get('error')}")
                    now = time.monotonic()
                    carry += phase.rate_rps * (now - last)
                    last = now
                    n = int(carry)
                    if n:
                        carry -= n
                        stamp = time.time()
                        n_batch = sum(
                            self._tenant_rng.random() < self.batch_frac
                            for _ in range(n)) if self.two_tenant \
                            else 0
                        with self._q_lock:
                            self._queue.extend(
                                [stamp] * (n - n_batch))
                            self._queue_batch.extend(
                                [stamp] * n_batch)
                        self._generated += n
                    if kill_at is not None and now >= kill_at[0]:
                        victims = self._runner.terminate_domain(
                            kill_at[1])
                        driver.event("day.rack_kill",
                                     domain=kill_at[1],
                                     victims=victims,
                                     phase=phase.name)
                        kill_fired = {"domain": kill_at[1],
                                      "victims": victims}
                        kill_at = None
                    time.sleep(0.005)
            # drain: the day is over when every admitted request has a
            # logged completion (dropped == 0 is a --check gate)
            self._phase_name = "drain"
            drain_deadline = time.monotonic() + self.drain_timeout_s
            while time.monotonic() < drain_deadline:
                with self._done_lock:
                    done = self._completed
                if done >= self._generated:
                    break
                time.sleep(0.01)
        finally:
            driver.event("day.load", generated=self._generated,
                         completed=self._completed,
                         completed_batch=(self._completed_batch
                                          if self.two_tenant
                                          else None))
            driver.event("day.end")
            self._day_over.set()
            sup_thread.join(timeout=20.0)
            if sup_thread.is_alive():
                supervisor.request_stop()
                sup_thread.join(timeout=10.0)
            if self._runner is not None:
                self._runner.shutdown()
            driver.close()
        wall = time.time() - t0
        return {
            "seed": self.seed,
            "domain_spread": self.domain_spread,
            "logdir": self.logdir,
            "wall_s": round(wall, 3),
            "generated": self._generated,
            "completed": self._completed,
            "two_tenant": ({"batch_completed": self._completed_batch,
                            "interactive_completed":
                                self._completed
                                - self._completed_batch}
                           if self.two_tenant else None),
            "phases": [dataclasses.asdict(p) for p in self.phases],
            "rack_kill": kill_fired,
            "scales_applied": supervisor.scales_applied,
            "generations": supervisor.generation + 1,
            "final_workers": supervisor.num_workers,
            "completed_run": "result" in outcome,
            "error": (str(outcome["error"]) if "error" in outcome
                      else None),
        }
