"""Reusable Strategy-API conformance suite.

≙ strategy_test_lib.py (reference: tensorflow/python/distribute/
strategy_test_lib.py, 825 LoC — SURVEY.md §4 "effectively the Strategy
API contract"). Any Strategy implementation — including out-of-tree
ones — can validate itself:

    class TestMyStrategy(StrategyConformance):
        def make_strategy(self):
            return MyStrategy(...)

Each check is a ``check_*`` method; the ``test_conformance`` entry point
runs them all and reports every failure (not just the first).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.parallel.collectives import ReduceOp
from distributed_tensorflow_tpu.parallel.strategy import (
    get_replica_context, get_strategy, has_strategy)
from distributed_tensorflow_tpu.parallel.values import (
    PerReplica,
    VariableAggregation,
    VariableSynchronization,
)


class StrategyConformance:
    """Subclass and implement ``make_strategy``; pytest collects
    ``test_conformance``."""

    def make_strategy(self):
        raise NotImplementedError

    # -- individual contract checks --------------------------------------

    def check_num_replicas_positive(self, s):
        assert s.num_replicas_in_sync >= 1

    def check_scope_registers_strategy(self, s):
        assert not has_strategy()
        with s.scope():
            assert has_strategy()
            assert get_strategy() is s
        assert not has_strategy()

    def check_variable_creation_in_scope(self, s):
        with s.scope():
            v = s.create_variable(jnp.ones((2, 2)), name="w")
        assert v.shape == (2, 2)
        np.testing.assert_allclose(np.asarray(v.read_value()),
                                   np.ones((2, 2)))
        assert s.extended.variable_created_in_scope(v)

    def check_run_executes_per_replica(self, s):
        n = s.num_replicas_in_sync

        def fn():
            ctx = get_replica_context()
            return ctx.replica_id_in_sync_group

        out = s.run(fn)
        ids = sorted(int(x) for x in (out.values if isinstance(
            out, PerReplica) else [out]))
        assert ids == list(range(n)), ids

    def check_all_reduce_sums_across_replicas(self, s):
        n = s.num_replicas_in_sync

        def fn():
            ctx = get_replica_context()
            return ctx.all_reduce(ReduceOp.SUM, jnp.asarray(1.0))

        out = s.run(fn)
        vals = out.values if isinstance(out, PerReplica) else [out]
        for v in vals:
            assert float(jnp.squeeze(jnp.asarray(v))) == float(n), vals

    def check_reduce_mean(self, s):
        def fn():
            ctx = get_replica_context()
            return jnp.asarray(float(1 + ctx.replica_id_in_sync_group)) \
                if not isinstance(ctx.replica_id_in_sync_group, jax.Array) \
                else (ctx.replica_id_in_sync_group + 1.0)

        out = s.run(fn)
        red = s.reduce(ReduceOp.MEAN, out, axis=None)
        n = s.num_replicas_in_sync
        expected = (n + 1) / 2
        np.testing.assert_allclose(float(jnp.asarray(red)), expected,
                                   rtol=1e-6)

    def check_variable_update_visible_after_run(self, s):
        with s.scope():
            v = s.create_variable(jnp.zeros(()), name="counter")

        def fn():
            v.assign_add(1.0)

        s.run(fn)
        # on-write mirrored variables aggregate identical updates
        np.testing.assert_allclose(float(jnp.asarray(v.read_value())), 1.0)

    def check_experimental_distribute_values(self, s):
        n = s.num_replicas_in_sync
        vals = s.experimental_distribute_values_from_function(
            lambda ctx: float(ctx.replica_id_in_sync_group))
        assert isinstance(vals, PerReplica)
        assert [float(x) for x in vals.values] == [float(i)
                                                   for i in range(n)]

    def check_gather(self, s):
        def fn():
            ctx = get_replica_context()
            rid = ctx.replica_id_in_sync_group
            base = (jnp.asarray(rid, jnp.float32)
                    if not isinstance(rid, jax.Array)
                    else rid.astype(jnp.float32))
            return jnp.reshape(base, (1,))

        out = s.run(fn)
        gathered = s.gather(out, axis=0)
        assert gathered.shape[0] == s.num_replicas_in_sync

    # -- nested scope (≙ strategy_test_lib nested-scope contract) ---------

    def check_nested_scope_restores_outer(self, s):
        s2 = self.make_strategy()
        with s.scope():
            assert get_strategy() is s
            with s2.scope():
                assert get_strategy() is s2
            assert get_strategy() is s
        assert not has_strategy()

    def check_scope_reentrant(self, s):
        with s.scope():
            with s.scope():
                assert get_strategy() is s
            assert get_strategy() is s

    # -- VariableAggregation write matrix (≙ values.py OnWrite :1705) -----

    def _rid(self):
        ctx = get_replica_context()
        rid = ctx.replica_id_in_sync_group
        return (rid.astype(jnp.float32) if isinstance(rid, jax.Array)
                else jnp.asarray(float(rid)))

    def check_on_write_aggregation_mean(self, s):
        n = s.num_replicas_in_sync
        with s.scope():
            v = s.create_variable(jnp.zeros(()), name="m",
                                  aggregation=VariableAggregation.MEAN)
        s.run(lambda: v.assign(self._rid()))
        np.testing.assert_allclose(float(np.asarray(v.read_value())),
                                   (n - 1) / 2, rtol=1e-6)

    def check_on_write_aggregation_sum(self, s):
        n = s.num_replicas_in_sync
        with s.scope():
            v = s.create_variable(jnp.zeros(()), name="sm",
                                  aggregation=VariableAggregation.SUM)
        s.run(lambda: v.assign(self._rid() + 1.0))
        np.testing.assert_allclose(float(np.asarray(v.read_value())),
                                   n * (n + 1) / 2, rtol=1e-6)

    def check_on_write_aggregation_only_first_replica(self, s):
        with s.scope():
            v = s.create_variable(
                jnp.zeros(()), name="f",
                aggregation=VariableAggregation.ONLY_FIRST_REPLICA)
        s.run(lambda: v.assign(self._rid() + 7.0))
        # replica 0's write wins everywhere
        np.testing.assert_allclose(float(np.asarray(v.read_value())), 7.0)

    def check_sync_on_read_sum(self, s):
        n = s.num_replicas_in_sync
        with s.scope():
            v = s.create_variable(
                jnp.zeros(()), name="metric",
                synchronization=VariableSynchronization.ON_READ,
                aggregation=VariableAggregation.SUM)
        s.run(lambda: v.assign_add(jnp.asarray(1.0)))
        # each replica accumulated locally; global read sums
        np.testing.assert_allclose(float(np.asarray(v.read_value())),
                                   float(n))

    def check_sync_on_read_accumulates_across_runs(self, s):
        n = s.num_replicas_in_sync
        with s.scope():
            v = s.create_variable(
                jnp.zeros(()), name="metric2",
                synchronization=VariableSynchronization.ON_READ,
                aggregation=VariableAggregation.SUM)

        def fn():
            v.assign_add(jnp.asarray(1.0))

        s.run(fn)
        s.run(fn)
        np.testing.assert_allclose(float(np.asarray(v.read_value())),
                                   2.0 * n)

    # -- input iteration through the strategy (≙ strategy_test_lib
    #    _test_input_fn_iterable / minimize_loss contracts) ----------------

    def check_distribute_dataset_iteration(self, s):
        from distributed_tensorflow_tpu.input.dataset import Dataset
        n = s.num_replicas_in_sync
        data = np.arange(4 * n * 2, dtype=np.float32).reshape(4 * n, 2)
        ds = Dataset.from_tensor_slices(data).batch(n)
        dist = s.experimental_distribute_dataset(ds)
        seen = []
        for batch in dist:
            assert batch.shape == (n, 2), batch.shape
            seen.append(np.asarray(batch))
        np.testing.assert_allclose(np.concatenate(seen, axis=0), data)

    def check_distributed_batch_feeds_run(self, s):
        from distributed_tensorflow_tpu.input.dataset import Dataset
        n = s.num_replicas_in_sync
        data = np.ones((2 * n, 3), np.float32)
        dist = s.experimental_distribute_dataset(
            Dataset.from_tensor_slices(data).batch(n))
        batch = next(iter(dist))

        def fn(b):
            ctx = get_replica_context()
            return ctx.all_reduce(ReduceOp.SUM, jnp.sum(b))

        out = s.run(fn, args=(batch,))
        vals = out.values if isinstance(out, PerReplica) else [out]
        np.testing.assert_allclose(float(np.asarray(vals[0])), 3.0 * n)

    def check_distribute_values_feed_run(self, s):
        n = s.num_replicas_in_sync
        vals = s.experimental_distribute_values_from_function(
            lambda ctx: np.asarray([float(ctx.replica_id_in_sync_group)],
                                   np.float32))
        out = s.run(lambda x: x * 2.0, args=(vals,))
        got = sorted(float(np.asarray(v).ravel()[0]) for v in out.values)
        assert got == [2.0 * i for i in range(n)], got

    # -- merge_call / optimizer pattern (≙ mirrored_run.py:433 +
    #    strategy_test_lib minimize-with-merge_call) -----------------------

    def check_merge_call_reduces(self, s):
        n = s.num_replicas_in_sync

        def fn():
            ctx = get_replica_context()
            grad = self._rid() + 1.0

            def merge(strategy, g):
                return strategy.extended.reduce_to(ReduceOp.SUM, g)

            return ctx.merge_call(merge, args=(grad,))

        out = s.run(fn)
        vals = out.values if isinstance(out, PerReplica) else [out]
        for v in vals:
            np.testing.assert_allclose(float(np.asarray(v)),
                                       n * (n + 1) / 2, rtol=1e-6)

    def check_merge_call_optimizer_apply(self, s):
        """The classic optimizer shape: per-replica grads -> merge_call
        reduces -> extended.update applies once to the variable."""
        n = s.num_replicas_in_sync
        with s.scope():
            v = s.create_variable(jnp.asarray(10.0), name="w")

        def fn():
            ctx = get_replica_context()
            grad = self._rid() + 1.0          # mean = (n+1)/2

            def merge(strategy, g):
                g = strategy.extended.reduce_to(ReduceOp.MEAN, g)
                strategy.extended.update(
                    v, lambda var, gg: var.assign_sub(gg), args=(g,))

            ctx.merge_call(merge, args=(grad,))

        s.run(fn)
        np.testing.assert_allclose(float(np.asarray(v.read_value())),
                                   10.0 - (n + 1) / 2, rtol=1e-6)

    # -- replica collectives beyond all_reduce ----------------------------

    def check_all_gather_in_replica_context(self, s):
        n = s.num_replicas_in_sync

        def fn():
            ctx = get_replica_context()
            return ctx.all_gather(jnp.reshape(self._rid(), (1,)), axis=0)

        out = s.run(fn)
        vals = out.values if isinstance(out, PerReplica) else [out]
        np.testing.assert_allclose(np.sort(np.asarray(vals[0]).ravel()),
                                   np.arange(n, dtype=np.float32))

    def check_gather_preserves_replica_order(self, s):
        n = s.num_replicas_in_sync
        out = s.run(lambda: jnp.reshape(self._rid(), (1,)))
        gathered = np.asarray(s.gather(out, axis=0)).ravel()
        np.testing.assert_allclose(gathered,
                                   np.arange(n, dtype=np.float32))

    def check_reduce_with_axis(self, s):
        out = s.run(lambda: jnp.asarray([1.0, 3.0]))
        red = s.reduce(ReduceOp.MEAN, out, axis=0)
        np.testing.assert_allclose(float(np.asarray(red)), 2.0, rtol=1e-6)

    def check_run_twice_consistent(self, s):
        """The compiled-run cache must not corrupt repeat executions."""
        with s.scope():
            v = s.create_variable(jnp.zeros(()), name="c2")

        def fn():
            v.assign_add(1.0)

        s.run(fn)
        s.run(fn)
        np.testing.assert_allclose(float(np.asarray(v.read_value())), 2.0)

    def check_value_context_fields(self, s):
        n = s.num_replicas_in_sync
        seen = []
        s.experimental_distribute_values_from_function(
            lambda ctx: seen.append((ctx.replica_id_in_sync_group,
                                     ctx.num_replicas_in_sync)))
        assert seen == [(i, n) for i in range(n)], seen

    # -- entry point ------------------------------------------------------

    def test_conformance(self, devices):
        failures = []
        for name in [m for m in dir(self) if m.startswith("check_")]:
            s = self.make_strategy()
            try:
                getattr(self, name)(s)
            except NotImplementedError:
                pass      # optional surface for this strategy kind
            except AssertionError as e:
                failures.append(f"{name}: {e}")
            except Exception as e:  # noqa: BLE001 - report, keep going
                failures.append(f"{name}: {type(e).__name__}: {e}")
        assert not failures, ("strategy contract violations:\n  "
                              + "\n  ".join(failures))
