"""Reusable Strategy-API conformance suite.

≙ strategy_test_lib.py (reference: tensorflow/python/distribute/
strategy_test_lib.py, 825 LoC — SURVEY.md §4 "effectively the Strategy
API contract"). Any Strategy implementation — including out-of-tree
ones — can validate itself:

    class TestMyStrategy(StrategyConformance):
        def make_strategy(self):
            return MyStrategy(...)

Each check is a ``check_*`` method; the ``test_conformance`` entry point
runs them all and reports every failure (not just the first).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.parallel.collectives import ReduceOp
from distributed_tensorflow_tpu.parallel.strategy import (
    get_replica_context, get_strategy, has_strategy)
from distributed_tensorflow_tpu.parallel.values import PerReplica


class StrategyConformance:
    """Subclass and implement ``make_strategy``; pytest collects
    ``test_conformance``."""

    def make_strategy(self):
        raise NotImplementedError

    # -- individual contract checks --------------------------------------

    def check_num_replicas_positive(self, s):
        assert s.num_replicas_in_sync >= 1

    def check_scope_registers_strategy(self, s):
        assert not has_strategy()
        with s.scope():
            assert has_strategy()
            assert get_strategy() is s
        assert not has_strategy()

    def check_variable_creation_in_scope(self, s):
        with s.scope():
            v = s.create_variable(jnp.ones((2, 2)), name="w")
        assert v.shape == (2, 2)
        np.testing.assert_allclose(np.asarray(v.read_value()),
                                   np.ones((2, 2)))
        assert s.extended.variable_created_in_scope(v)

    def check_run_executes_per_replica(self, s):
        n = s.num_replicas_in_sync

        def fn():
            ctx = get_replica_context()
            return ctx.replica_id_in_sync_group

        out = s.run(fn)
        ids = sorted(int(x) for x in (out.values if isinstance(
            out, PerReplica) else [out]))
        assert ids == list(range(n)), ids

    def check_all_reduce_sums_across_replicas(self, s):
        n = s.num_replicas_in_sync

        def fn():
            ctx = get_replica_context()
            return ctx.all_reduce(ReduceOp.SUM, jnp.asarray(1.0))

        out = s.run(fn)
        vals = out.values if isinstance(out, PerReplica) else [out]
        for v in vals:
            assert float(jnp.squeeze(jnp.asarray(v))) == float(n), vals

    def check_reduce_mean(self, s):
        def fn():
            ctx = get_replica_context()
            return jnp.asarray(float(1 + ctx.replica_id_in_sync_group)) \
                if not isinstance(ctx.replica_id_in_sync_group, jax.Array) \
                else (ctx.replica_id_in_sync_group + 1.0)

        out = s.run(fn)
        red = s.reduce(ReduceOp.MEAN, out, axis=None)
        n = s.num_replicas_in_sync
        expected = (n + 1) / 2
        np.testing.assert_allclose(float(jnp.asarray(red)), expected,
                                   rtol=1e-6)

    def check_variable_update_visible_after_run(self, s):
        with s.scope():
            v = s.create_variable(jnp.zeros(()), name="counter")

        def fn():
            v.assign_add(1.0)

        s.run(fn)
        # on-write mirrored variables aggregate identical updates
        np.testing.assert_allclose(float(jnp.asarray(v.read_value())), 1.0)

    def check_experimental_distribute_values(self, s):
        n = s.num_replicas_in_sync
        vals = s.experimental_distribute_values_from_function(
            lambda ctx: float(ctx.replica_id_in_sync_group))
        assert isinstance(vals, PerReplica)
        assert [float(x) for x in vals.values] == [float(i)
                                                   for i in range(n)]

    def check_gather(self, s):
        def fn():
            ctx = get_replica_context()
            rid = ctx.replica_id_in_sync_group
            base = (jnp.asarray(rid, jnp.float32)
                    if not isinstance(rid, jax.Array)
                    else rid.astype(jnp.float32))
            return jnp.reshape(base, (1,))

        out = s.run(fn)
        gathered = s.gather(out, axis=0)
        assert gathered.shape[0] == s.num_replicas_in_sync

    # -- entry point ------------------------------------------------------

    CHECKS = [name for name in sorted(dir()) if name.startswith("check_")]

    def test_conformance(self, devices):
        failures = []
        for name in [m for m in dir(self) if m.startswith("check_")]:
            s = self.make_strategy()
            try:
                getattr(self, name)(s)
            except NotImplementedError:
                pass      # optional surface for this strategy kind
            except AssertionError as e:
                failures.append(f"{name}: {e}")
            except Exception as e:  # noqa: BLE001 - report, keep going
                failures.append(f"{name}: {type(e).__name__}: {e}")
        assert not failures, ("strategy contract violations:\n  "
                              + "\n  ".join(failures))
