"""Simulated-fleet harness: hundreds of workers, one process, real code.

Every "at scale" claim of the control plane — elastic recovery
(resilience/supervisor.py), fleet-merged metrics (telemetry/
aggregate.py), barriers and KV liveness (cluster/coordination.py) — is
untestable on a 1-core container if testing it needs a process (let
alone a chip) per worker. This harness runs **N lightweight worker
loops as threads of one process**, all driving the *real* modules:

- the real :class:`~distributed_tensorflow_tpu.cluster.coordination.
  _LocalService` is the shared KV/barrier backend (the same code the
  single-process production fallback runs); each simulated worker
  holds a :class:`SimAgent` — a real ``CoordinationServiceAgent``
  whose identity (pid, N) is simulated but whose every op goes through
  the production method bodies, generation namespacing, chaos sites
  and op counting included;
- the real :class:`~distributed_tensorflow_tpu.resilience.supervisor.
  RecoverySupervisor` watch/recover/reform loop supervises the fleet —
  only its spawn primitive is swapped (:class:`SimRunner`, threads
  instead of processes) via the supervisor's injectable
  ``runner_factory``, plus the sharded-KV heartbeat source and the
  generation GC it already supports;
- the real tree-rollup path (telemetry/aggregate.py) aggregates every
  worker's metrics registry, and the real seeded chaos layer
  (resilience/faults.py, site ``fleet.step``) drives crash / stall /
  partition faults deterministically.

Worker death is cooperative: ``SimRunner.terminate`` marks the task
dead **immediately** (exit code ``-SIGKILL``, what the supervisor
sees) and flags the thread, which exits at its next step boundary —
until then it is exactly the straggler a real SIGKILL survivor's
in-flight RPCs are, which the generation namespace must (and does)
fence off.

What this cannot simulate: real network latency/loss, true process
isolation, per-host clocks, and the GIL serializes "parallel" steps —
absolute throughput numbers are lower bounds with honest caveats
(README "Fleet scale"); *scaling shapes* (ops vs N, fan-in vs N,
detect latency vs N) are the product.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import random
import re
import tempfile
import threading
import time
import traceback
from typing import Callable

from distributed_tensorflow_tpu.cluster import coordination, elastic, kv_gc
from distributed_tensorflow_tpu.resilience import faults
from distributed_tensorflow_tpu.resilience import heartbeats as hb
from distributed_tensorflow_tpu.resilience.retry import Backoff, RetryPolicy
from distributed_tensorflow_tpu.resilience.supervisor import (
    RecoverySupervisor,
)
from distributed_tensorflow_tpu.telemetry import aggregate
from distributed_tensorflow_tpu.telemetry import registry as _registry
from distributed_tensorflow_tpu.testing import multi_process_runner as mpr

_SIGKILL = 9

#: supervisor stall detail: "no heartbeat for X.Xs (budget Ys)"
_STALL_RE = re.compile(r"no heartbeat for ([0-9.]+)s \(budget ([0-9.]+)")

#: Per-task env var naming the failure domain (rack) the simulated
#: worker is placed in — the placement fact placement-aware layers
#: (peer-snapshot ring, data-service leases) consume.
ENV_FAILURE_DOMAIN = "DTX_FAILURE_DOMAIN"


class DomainTopology:
    """pid → failure domain (rack/host) mapping of a simulated fleet.

    Contiguous block placement — ``rack = pid // workers_per_domain`` —
    deliberately mirrors how real schedulers pack consecutive task ids
    onto the same rack, which is exactly the placement that makes the
    blind ``(pid - 1) % N`` replica ring lose data under a rack kill
    (adjacent pids share a domain, so an owner and its replicator die
    together). The last domain may be short when ``num_workers`` is not
    a multiple of ``workers_per_domain``.
    """

    def __init__(self, num_workers: int, *, workers_per_domain: int = 4,
                 prefix: str = "rack"):
        if num_workers < 1:
            raise ValueError(f"num_workers must be >= 1, got {num_workers}")
        if workers_per_domain < 1:
            raise ValueError(f"workers_per_domain must be >= 1, got "
                             f"{workers_per_domain}")
        self.num_workers = int(num_workers)
        self.workers_per_domain = int(workers_per_domain)
        self.prefix = prefix

    @property
    def num_domains(self) -> int:
        return -(-self.num_workers // self.workers_per_domain)

    def domain_of(self, pid: int) -> str:
        if not 0 <= pid < self.num_workers:
            raise ValueError(f"pid {pid} outside fleet of "
                             f"{self.num_workers}")
        return f"{self.prefix}{pid // self.workers_per_domain}"

    @property
    def domains(self) -> "list[str]":
        return [f"{self.prefix}{d}" for d in range(self.num_domains)]

    def members(self, domain: str) -> "list[int]":
        return [p for p in range(self.num_workers)
                if self.domain_of(p) == domain]

    def as_map(self) -> "dict[int, str]":
        """{pid: domain} — the wire/placement-policy shape
        (checkpoint/peer_snapshot.assign_replicators, the data-service
        dispatcher's ``domains=``)."""
        return {p: self.domain_of(p) for p in range(self.num_workers)}

    def shrink(self, num_workers: int) -> "DomainTopology":
        """The same placement over a resized fleet (elastic scale keeps
        machines where they are; slots beyond the new size vanish)."""
        return DomainTopology(num_workers,
                              workers_per_domain=self.workers_per_domain,
                              prefix=self.prefix)


@dataclasses.dataclass(frozen=True)
class DomainKill:
    """One correlated failure: every worker of ``domain`` dies at once,
    ``after_s`` seconds into the run."""

    domain: str
    victims: tuple
    after_s: float


class _SimKilled(BaseException):
    """Raised inside a worker thread whose task was terminated (it is a
    BaseException so no retry/except-Exception layer swallows it)."""


class SimAgent(coordination.CoordinationServiceAgent):
    """A real CoordinationServiceAgent with simulated identity.

    ``_client`` is pinned to None so every op takes the production
    in-process path against the SHARED ``_LocalService`` instance;
    ``process_id``/``num_processes`` come from the simulated cluster,
    which is what turns the agent's ``barrier`` into a true N-party
    barrier. ``partition()`` models a network partition: every KV op
    raises ``CoordinationError`` until ``heal()``.
    """

    def __init__(self, service: coordination._LocalService,
                 pid: int, num_workers: int):
        super().__init__()
        self._local = service
        self._pid = pid
        self._n = num_workers
        self._partitioned = threading.Event()

    @property
    def _client(self):
        return None

    @property
    def process_id(self) -> int:
        return self._pid

    @property
    def num_processes(self) -> int:
        return self._n

    # -- simulated partition ----------------------------------------------
    def partition(self):
        self._partitioned.set()

    def heal(self):
        self._partitioned.clear()

    @property
    def partitioned(self) -> bool:
        return self._partitioned.is_set()

    def _check_net(self):
        if self._partitioned.is_set():
            raise coordination.CoordinationError(
                f"simulated network partition: worker {self._pid} "
                f"cannot reach the coordination service")

    def key_value_set(self, *a, **k):
        self._check_net()
        return super().key_value_set(*a, **k)

    def key_value_get(self, *a, **k):
        self._check_net()
        return super().key_value_get(*a, **k)

    def key_value_try_get(self, *a, **k):
        self._check_net()
        return super().key_value_try_get(*a, **k)

    def key_value_dir_get(self, *a, **k):
        self._check_net()
        return super().key_value_dir_get(*a, **k)

    def key_value_delete(self, *a, **k):
        self._check_net()
        return super().key_value_delete(*a, **k)

    def key_value_increment(self, *a, **k):
        self._check_net()
        return super().key_value_increment(*a, **k)

    def barrier(self, *a, **k):
        self._check_net()
        return super().barrier(*a, **k)


def make_sim_cluster(num_workers: int,
                     service: "coordination._LocalService | None" = None
                     ) -> "list[SimAgent]":
    """N agents sharing one in-memory service — the smallest useful
    slice of the harness (direct barrier/KV tests)."""
    service = service or coordination._LocalService()
    return [SimAgent(service, p, num_workers) for p in range(num_workers)]


def sim_cluster_spec(n: int) -> dict:
    """Portless cluster spec for thread-backed runners (the
    ``cluster_spec_fn`` a supervisor over a :class:`SimRunner` wants —
    resizable, so autoscaler-driven scale reforms work unchanged)."""
    return {"worker": [f"sim://{i}" for i in range(n)]}


@dataclasses.dataclass
class SimTaskContext:
    """What a simulated worker fn receives instead of a process env."""

    pid: int
    num_workers: int
    env: dict
    agent: SimAgent
    _kill: threading.Event

    @property
    def generation(self) -> int:
        try:
            return int(self.env.get(elastic.ENV_GENERATION, "0"))
        except ValueError:
            return 0

    @property
    def domain(self) -> "str | None":
        """The failure domain (rack) this task is placed in, when the
        runner was given a :class:`DomainTopology`."""
        return self.env.get(ENV_FAILURE_DOMAIN)

    def check_kill(self):
        if self._kill.is_set():
            raise _SimKilled()

    def sleep(self, seconds: float):
        """Kill-interruptible sleep."""
        if self._kill.wait(seconds):
            raise _SimKilled()


class _SimTask:
    def __init__(self, key):
        self.key = key
        self.kill = threading.Event()
        self.thread: "threading.Thread | None" = None
        self.exitcode: "int | None" = None
        self.error: "str | None" = None
        self.value = None
        self.exit_wall: "float | None" = None
        self._lock = threading.Lock()

    def mark_exit(self, code: int, error: "str | None" = None,
                  value=None) -> bool:
        """First exit report wins (a terminate beats the zombie thread's
        own later completion)."""
        with self._lock:
            if self.exitcode is not None:
                return False
            self.exitcode = code
            self.error = error
            self.value = value
            self.exit_wall = time.time()
            return True


class SimRunner:
    """Thread-backed stand-in for testing.multi_process_runner.
    MultiProcessRunner — same interface the RecoverySupervisor drives
    (poll/alive_tasks/terminate/terminate_all/join/reform), tasks are
    daemon threads running ``fn(SimTaskContext, *args, **kwargs)``.
    """

    #: thread stack size for simulated workers (the loops are shallow;
    #: the default 8 MiB per thread is pointless at N=1000)
    STACK_BYTES = 512 * 1024

    def __init__(self, fn: Callable, cluster_spec, *, args=(),
                 kwargs=None, env=None, devices_per_process=1,
                 timeout: float = 300.0, agent_factory=None,
                 on_generation=None,
                 topology: "DomainTopology | None" = None):
        del devices_per_process
        self._fn = fn
        self._spec = {k: list(v) for k, v in cluster_spec.items()}
        self._args = args
        self._kwargs = kwargs or {}
        self._env = dict(env or {})
        self._timeout = timeout
        self._agent_factory = agent_factory or (
            lambda pid, n: SimAgent(coordination._LocalService(), pid, n))
        self._on_generation = on_generation
        #: failure-domain placement of this generation's tasks; each
        #: task sees its own domain in ``env[ENV_FAILURE_DOMAIN]``
        self.topology = topology
        self._tasks: dict[tuple[str, int], _SimTask] = {}
        self._task_env: dict[tuple[str, int], dict] = {}
        self.history: list[mpr.TaskResult] = []
        #: every agent ever handed to a task (op-count accounting)
        self.agents: list[SimAgent] = []

    # -- lifecycle --------------------------------------------------------
    def _task_keys(self):
        return [(t, i) for t in sorted(self._spec)
                for i in range(len(self._spec[t]))]

    @property
    def num_tasks(self) -> int:
        return sum(len(v) for v in self._spec.values())

    def _spawn(self, key, env):
        task = _SimTask(key)
        n = self.num_tasks
        agent = self._agent_factory(key[1], n)
        self.agents.append(agent)
        env = dict(env)
        if self.topology is not None and key[1] < self.topology.num_workers:
            env[ENV_FAILURE_DOMAIN] = self.topology.domain_of(key[1])
        ctx = SimTaskContext(pid=key[1], num_workers=n, env=env,
                             agent=agent, _kill=task.kill)
        prev_stack = None
        with contextlib.suppress(ValueError, RuntimeError):
            prev_stack = threading.stack_size(self.STACK_BYTES)
        try:
            task.thread = threading.Thread(
                target=self._run_task, args=(task, ctx), daemon=True,
                name=f"sim-{key[0]}-{key[1]}")
            task.thread.start()
        finally:
            if prev_stack is not None:
                with contextlib.suppress(ValueError, RuntimeError):
                    threading.stack_size(prev_stack)
        self._tasks[key] = task
        self._task_env[key] = dict(env)

    def _run_task(self, task: _SimTask, ctx: SimTaskContext):
        try:
            value = self._fn(ctx, *self._args, **self._kwargs)
            task.mark_exit(0, value=value)
        except _SimKilled:
            pass                          # terminate() already marked it
        except SystemExit as e:
            code = e.code if isinstance(e.code, int) else \
                (0 if e.code is None else 1)
            task.mark_exit(code, error=None if code == 0
                           else f"SystemExit({e.code})")
        except BaseException:
            task.mark_exit(1, error=traceback.format_exc())

    def start(self):
        if self._on_generation is not None:
            self._on_generation(self._gen_of(self._env))
        for key in self._task_keys():
            self._spawn(key, self._env)
        return self

    @staticmethod
    def _gen_of(env) -> int:
        try:
            return int(env.get(elastic.ENV_GENERATION, "0"))
        except ValueError:
            return 0

    def reform(self, cluster_spec=None, *, env=None,
               allow_resize: bool = False):
        self.terminate_all()
        for key, t in self._tasks.items():
            self.history.append(mpr.TaskResult(
                task_type=key[0], task_id=key[1], exitcode=t.exitcode,
                value=t.value, error=t.error))
        if cluster_spec is not None:
            new = {k: list(v) for k, v in cluster_spec.items()}
            if not allow_resize and sorted(
                    (t, len(v)) for t, v in new.items()) != sorted(
                    (t, len(v)) for t, v in self._spec.items()):
                raise ValueError("reform must keep the cluster shape")
            self._spec = new
            if self.topology is not None:
                # elastic resize keeps machines where they are: the
                # same block placement over the new worker count
                self.topology = self.topology.shrink(
                    len(self._spec.get("worker", [])) or 1)
        self._tasks.clear()
        merged_env = dict(self._env)
        merged_env.update(env or {})
        self._env = merged_env
        if self._on_generation is not None:
            self._on_generation(self._gen_of(merged_env))
        for key in self._task_keys():
            self._spawn(key, merged_env)

    # -- the supervisor-facing surface ------------------------------------
    def poll(self) -> dict:
        return {k: t.exitcode for k, t in self._tasks.items()
                if t.exitcode is not None}

    def alive_tasks(self):
        return sorted(k for k, t in self._tasks.items()
                      if t.exitcode is None)

    def terminate(self, task_type: str, task_id: int):
        t = self._tasks[(task_type, task_id)]
        t.kill.set()
        t.mark_exit(-_SIGKILL)

    def terminate_domain(self, domain: str) -> "list[int]":
        """Correlated kill: every live worker placed in ``domain`` exits
        AT ONCE (all exits marked before any thread gets a chance to
        run — the supervisor observes one simultaneous multi-worker
        failure, not a cascade). Returns the task ids killed."""
        if self.topology is None:
            raise ValueError("terminate_domain needs a topology")
        killed = []
        for pid in self.topology.members(domain):
            t = self._tasks.get(("worker", pid))
            if t is not None and t.exitcode is None:
                t.kill.set()
                t.mark_exit(-_SIGKILL)
                killed.append(pid)
        return killed

    def terminate_all(self):
        for t in self._tasks.values():
            if t.exitcode is None:
                t.kill.set()
                t.mark_exit(-_SIGKILL)
            else:
                t.kill.set()              # reap any zombie thread

    def join(self, timeout: "float | None" = None,
             raise_on_error: bool = True) -> mpr.MultiProcessRunnerResult:
        deadline = time.monotonic() + (timeout or self._timeout)
        while any(t.exitcode is None for t in self._tasks.values()):
            if time.monotonic() > deadline:
                for t in self._tasks.values():
                    if t.exitcode is None:
                        t.kill.set()
                        t.mark_exit(-_SIGKILL)
                break
            time.sleep(0.01)
        results = {k: mpr.TaskResult(
            task_type=k[0], task_id=k[1], exitcode=t.exitcode,
            value=t.value, error=t.error)
            for k, t in self._tasks.items()}
        result = mpr.MultiProcessRunnerResult(results)
        if raise_on_error:
            bad = {k: t for k, t in results.items()
                   if t.error is not None or t.exitcode != 0}
            if bad:
                k = sorted(bad)[0]
                raise mpr.SubprocessError(
                    f"sim task {k} failed (exit {bad[k].exitcode}):\n"
                    f"{bad[k].error}", result)
        return result

    def shutdown(self, timeout: float = 5.0):
        """Reap every thread (tests must not leak zombies)."""
        self.terminate_all()
        deadline = time.monotonic() + timeout
        for t in self._tasks.values():
            if t.thread is not None:
                t.thread.join(max(0.0, deadline - time.monotonic()))

    def exit_wall(self, task_id: int) -> "float | None":
        t = self._tasks.get(("worker", task_id))
        return t.exit_wall if t is not None else None


# ---------------------------------------------------------------------------
# Seeded fault plans
# ---------------------------------------------------------------------------

def seeded_fleet_schedule(seed: int, num_workers: int, *,
                          kinds=("crash", "stall", "partition"),
                          step_range: "tuple[int, int]" = (3, 9),
                          stall_s: float = 2.0) -> faults.FaultSchedule:
    """A deterministic chaos schedule over the ``fleet.step`` site: one
    rule per kind, victim + step drawn from a string-seeded stream
    (the resilience/faults.py discipline — a pure function of the
    seed). ``stall_s`` must exceed the supervisor's staleness budget
    for the stall to be DETECTED rather than ridden out."""
    rng = random.Random(f"dtx-fleet:{seed}")
    rules = []
    for kind in kinds:
        victim = rng.randrange(num_workers)
        at = rng.randrange(*step_range)
        if kind == "crash":
            rules.append(faults.FaultRule(site="fleet.step",
                                          action="raise",
                                          tag=str(victim), hits=(at,)))
        elif kind == "stall":
            rules.append(faults.FaultRule(site="fleet.step",
                                          action="delay", delay_s=stall_s,
                                          tag=str(victim), hits=(at,)))
        elif kind == "partition":
            rules.append(faults.FaultRule(site="fleet.step",
                                          action="signal",
                                          tag=str(victim), hits=(at,)))
        else:
            raise ValueError(f"unknown fleet fault kind {kind!r}")
    return faults.FaultSchedule(rules=tuple(rules), seed=seed)


def seeded_domain_kill_plan(seed: int, topology: DomainTopology, *,
                            kills: int = 1,
                            after_range: "tuple[float, float]" = (0.5, 1.5),
                            eligible: "tuple | list | None" = None
                            ) -> "list[DomainKill]":
    """Seed-derived CORRELATED failures: each kill takes a whole
    failure domain down at once (a rack loses power: every worker in
    it exits together — the failure mode the placement policy exists
    for, which independent per-worker kill plans can never produce).
    Victim domains and kill instants are a pure function of the seed
    (the resilience/faults.py string-seeded discipline); ``eligible``
    restricts the candidate domains (e.g. racks that hold trainers)."""
    rng = random.Random(f"dtx-domain-kill:{seed}")
    cands = list(eligible) if eligible is not None else topology.domains
    if not cands:
        return []
    victims = rng.sample(cands, k=min(kills, len(cands)))
    return [DomainKill(domain=d,
                       victims=tuple(topology.members(d)),
                       after_s=round(rng.uniform(*after_range), 3))
            for d in victims]


# ---------------------------------------------------------------------------
# The harness
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class FleetReport:
    """What one FleetSim.run measured (bench.py --fleet's raw rows)."""

    num_workers: int
    steps: int
    wall_s: float
    completed: bool
    generations: int
    restarts: int
    #: KV ops by every WORKER agent, total and by op type
    worker_ops_total: int = 0
    worker_ops_by_type: dict = dataclasses.field(default_factory=dict)
    #: the busiest single agent's ops (the tree root reducer — the
    #: fan-in bottleneck the flat scheme put on the coordinator)
    max_agent_ops: int = 0
    #: supervisor-side heartbeat reads (sharded: O(N/shard) per tick)
    supervisor_ops_total: int = 0
    ops_per_sec: float = 0.0
    ops_per_worker_per_step: float = 0.0
    max_agent_ops_per_step: float = 0.0
    #: per-collect staleness of worker snapshots at the tree root
    rollup_latency_s_mean: "float | None" = None
    rollup_latency_s_max: "float | None" = None
    rollup_collects: int = 0
    rollup_workers_seen: int = 0
    #: barrier wall span (first arrival -> last release), when measured
    barrier_span_s: "float | None" = None
    #: per-failure detection/recovery timings from supervisor events
    detections: list = dataclasses.field(default_factory=list)
    detect_s_max: "float | None" = None
    mttr_s_max: "float | None" = None
    faults_fired: list = dataclasses.field(default_factory=list)
    kv_keys_final: int = 0
    kv_waiters_woken: int = 0
    swept_generations: list = dataclasses.field(default_factory=list)
    failures: list = dataclasses.field(default_factory=list)
    #: autoscaler-style scale reforms applied mid-run (``scale_plan``)
    scales_applied: int = 0
    scale_generations: list = dataclasses.field(default_factory=list)
    final_workers: int = 0
    error: "str | None" = None

    def to_row(self) -> dict:
        row = dataclasses.asdict(self)
        row["detections"] = [dict(d) for d in self.detections]
        return row


class FleetSim:
    """One simulated fleet run: N worker loops under the real
    RecoverySupervisor, sharded heartbeats, tree rollups, seeded chaos
    and generation GC, measured end to end.

    Worker loop per step: chaos site -> heartbeat (sharded publisher)
    -> metrics count -> periodic snapshot publish + reducer duties ->
    optional full-fleet barrier -> paced sleep. Pid 0 additionally
    publishes the generation's ``fleet/config`` key, which every other
    worker blocks on at generation start (the realistic reform
    thundering-herd the per-key-wakeup KV fix and decorrelated retry
    jitter exist for).
    """

    def __init__(self, num_workers: int, *,
                 steps: int = 12,
                 step_s: float = 0.01,
                 publish_every: int = 2,
                 fanout: int = 16,
                 hb_shard_size: int = 32,
                 barrier_at_step: "int | None" = None,
                 barrier_timeout_s: float = 30.0,
                 fault_schedule: "faults.FaultSchedule | None" = None,
                 partition_steps: int = 2,
                 stall_timeout_s: float = 1.0,
                 heartbeat_grace_s: float = 20.0,
                 max_restarts: int = 4,
                 gc_grace_s: float = 0.5,
                 collect_interval_s: float = 0.1,
                 generation_timeout_s: float = 120.0,
                 telemetry_dir: "str | None" = None,
                 scale_plan: "tuple | list" = (),
                 seed: int = 0):
        self.num_workers = num_workers
        self.steps = steps
        self.step_s = step_s
        self.publish_every = publish_every
        self.tree = aggregate.RollupTopology(num_workers, fanout=fanout)
        self.hb_shard_size = hb_shard_size
        self.barrier_at_step = barrier_at_step
        self.barrier_timeout_s = barrier_timeout_s
        self.fault_schedule = fault_schedule
        self.partition_steps = partition_steps
        self.stall_timeout_s = stall_timeout_s
        self.heartbeat_grace_s = heartbeat_grace_s
        self.max_restarts = max_restarts
        self.gc_grace_s = gc_grace_s
        self.collect_interval_s = collect_interval_s
        self.generation_timeout_s = generation_timeout_s
        self.telemetry_dir = telemetry_dir
        #: simulated scale events: ``[(after_s, target), ...]`` —
        #: ``after_s`` seconds into the run, ``request_scale(target)``
        #: lands on the real supervisor (same reform path the
        #: autoscaler drives). Targets must stay <= the construction-
        #: time ``num_workers``: the rollup topology is sized once.
        self.scale_plan = list(scale_plan)
        self.seed = seed
        self.kv = coordination._LocalService()
        self.current_gen = 0
        self._runner: "SimRunner | None" = None
        self._barrier_walls: dict[int, tuple] = {}
        self._barrier_lock = threading.Lock()

    # -- worker side ------------------------------------------------------
    def _worker_main(self, ctx: SimTaskContext):
        gen = ctx.generation
        with elastic.generation_override(gen):
            reg = _registry.MetricsRegistry()
            steps_done = reg.counter("training/steps_completed",
                                     "simulated steps")
            pub = hb.ShardedHeartbeatPublisher(
                ctx.agent, pid=ctx.pid, num_workers=ctx.num_workers,
                shard_size=self.hb_shard_size)
            backoff = Backoff(RetryPolicy(
                initial_backoff_s=0.005, max_backoff_s=0.1,
                decorrelated=True, seed=hash((self.seed, gen, ctx.pid))))
            if ctx.pid == 0:
                ctx.agent.key_value_set("fleet/config", json.dumps(
                    {"generation": gen, "num_workers": ctx.num_workers}))
            else:
                self._await_config(ctx, backoff)
            partition_left = 0
            for step in range(1, self.steps + 1):
                ctx.check_kill()
                if partition_left > 0:
                    partition_left -= 1
                    if partition_left == 0:
                        ctx.agent.heal()
                    ctx.sleep(self.step_s)
                    continue
                # beat BEFORE the chaos site: a worker that stalls (or
                # crashes) mid-step has already reported this step, so
                # supervisor-side detection runs on heartbeat
                # STALENESS, never on the (much larger) first-beat
                # grace budget
                pub.beat(step)
                decision = faults.fire("fleet.step", tag=ctx.pid)
                if decision is not None and decision.action == "signal":
                    partition_left = self.partition_steps
                    ctx.agent.partition()
                    ctx.sleep(self.step_s)
                    continue
                steps_done.increment()
                if step % self.publish_every == 0:
                    aggregate.publish_snapshot(
                        ctx.agent, reg, process_id=ctx.pid, seq=step)
                    aggregate.run_duties(ctx.agent, self.tree, ctx.pid)
                if self.barrier_at_step is not None \
                        and step == self.barrier_at_step:
                    arrive = time.time()
                    ctx.agent.barrier(f"fleet/step-{step}",
                                      timeout_s=self.barrier_timeout_s)
                    with self._barrier_lock:
                        self._barrier_walls[ctx.pid] = (arrive,
                                                        time.time())
                ctx.sleep(self.step_s)
            # final snapshot so short runs are visible at the root
            aggregate.publish_snapshot(ctx.agent, reg,
                                       process_id=ctx.pid, seq=self.steps)
            aggregate.run_duties(ctx.agent, self.tree, ctx.pid)
            return ctx.pid

    def _await_config(self, ctx: SimTaskContext, backoff: Backoff,
                      total_timeout_s: float = 30.0):
        """Blocking-get the generation config with kill-interruptible
        short reads + decorrelated-jitter pacing (the retry shape a real
        worker uses against a briefly unreachable coordinator)."""
        deadline = time.monotonic() + total_timeout_s
        while True:
            ctx.check_kill()
            try:
                ctx.agent.key_value_get("fleet/config", timeout_s=0.3)
                return
            except coordination.CoordinationError:
                if time.monotonic() > deadline:
                    raise
                d = min(backoff.next_s(),
                        max(deadline - time.monotonic(), 0.0))
                if d > 0:
                    ctx.sleep(d)

    # -- supervisor plumbing ----------------------------------------------
    def _agent(self, pid: int, num_workers: int) -> SimAgent:
        return SimAgent(self.kv, pid, num_workers)

    def _runner_factory(self, fn, spec, **kw):
        kw.pop("agent_factory", None)
        self._runner = SimRunner(
            fn, spec, agent_factory=self._agent,
            on_generation=self._note_generation, **kw)
        return self._runner

    def _note_generation(self, gen: int):
        self.current_gen = gen

    @staticmethod
    def _spec_fn(n: int) -> dict:
        return {"worker": [f"sim://{i}" for i in range(n)]}

    # -- the run ----------------------------------------------------------
    def run(self) -> FleetReport:
        n = self.num_workers
        tdir = self.telemetry_dir or tempfile.mkdtemp(prefix="fleet_sim_")
        sup_agent = SimAgent(self.kv, n, n)      # off-fleet identity
        gc_agent = SimAgent(self.kv, n + 1, n)
        supervisor = RecoverySupervisor(
            self._worker_main, num_workers=n,
            max_restarts=self.max_restarts,
            retry_policy=RetryPolicy(
                max_attempts=self.max_restarts + 1,
                initial_backoff_s=0.02, backoff_multiplier=1.5,
                max_backoff_s=0.2),
            stall_timeout_s=self.stall_timeout_s,
            heartbeat_grace_s=self.heartbeat_grace_s,
            generation_timeout_s=self.generation_timeout_s,
            poll_interval_s=0.02,
            telemetry_dir=tdir,
            heartbeats=hb.ShardedKVHeartbeats(
                sup_agent, shard_size=self.hb_shard_size),
            runner_factory=self._runner_factory,
            cluster_spec_fn=self._spec_fn,
            kv_gc=kv_gc.GenerationGC(gc_agent, grace_s=self.gc_grace_s))
        # the supervisor auto-starts a metrics exporter when it has a
        # telemetry dir; that is live-health machinery, not control
        # plane — keep the sim's op accounting clean
        supervisor._start_exporter = lambda: None

        outcome: dict = {}

        def _drive():
            try:
                outcome["result"] = supervisor.run()
            except BaseException as e:          # noqa: BLE001
                outcome["error"] = e

        schedule_cm = (faults.inject(self.fault_schedule)
                       if self.fault_schedule is not None
                       else contextlib.nullcontext())
        lat_samples: list[float] = []
        collects = 0
        workers_seen = 0
        bad_targets = [tg for _, tg in self.scale_plan if tg > n]
        if bad_targets:
            raise ValueError(
                f"scale_plan targets {bad_targets} exceed the "
                f"construction-time fleet size {n} (the rollup "
                f"topology is sized once)")
        pending_scales = sorted(self.scale_plan)
        t0 = time.time()
        with schedule_cm as registry:
            sup_thread = threading.Thread(target=_drive, daemon=True,
                                          name="sim-supervisor")
            sup_thread.start()
            while sup_thread.is_alive():
                sup_thread.join(self.collect_interval_s)
                elapsed = time.time() - t0
                # simulated autoscaler: fire due scale events through
                # the REAL request_scale/reform path
                while pending_scales and elapsed >= pending_scales[0][0]:
                    _, target = pending_scales.pop(0)
                    supervisor.request_scale(target, reason="sim_scale")
                sample = self._collect_once(gc_agent)
                if sample is not None:
                    collects += 1
                    lat_samples.extend(sample[0])
                    workers_seen = max(workers_seen, sample[1])
            fired = (registry.events()
                     if registry is not None else [])
        wall = time.time() - t0
        if self._runner is not None:
            self._runner.shutdown()
        # settle sweep: propagate the workers' final partials to the
        # root deterministically (thread completion order otherwise
        # decides how much of the last tick reached it). Runs on its
        # own agent so worker op accounting stays clean; excluded from
        # the latency samples (post-run ages are not rollup latency).
        settle_agent = SimAgent(self.kv, n + 2, n)
        with elastic.generation_override(self.current_gen):
            for _ in range(self.tree.depth):
                for pid in range(n):
                    aggregate.run_duties(settle_agent, self.tree, pid)
        final = self._collect_once(gc_agent)
        if final is not None:
            workers_seen = max(workers_seen, final[1])

        report = FleetReport(
            num_workers=n, steps=self.steps, wall_s=round(wall, 3),
            completed="result" in outcome,
            generations=supervisor.generation + 1,
            restarts=supervisor.restarts_used,
            faults_fired=[{"site": s, "tag": t, "hit": h, "action": a}
                          for s, t, h, a, _ in fired],
            failures=[f.describe() for f in supervisor.history],
            error=(str(outcome.get("error"))
                   if "error" in outcome else None),
        )
        self._account_ops(report, sup_agent, gc_agent, wall)
        if lat_samples:
            report.rollup_latency_s_mean = round(
                sum(lat_samples) / len(lat_samples), 4)
            report.rollup_latency_s_max = round(max(lat_samples), 4)
        report.rollup_collects = collects
        report.rollup_workers_seen = workers_seen
        if self._barrier_walls:
            with self._barrier_lock:
                walls = dict(self._barrier_walls)
            report.barrier_span_s = round(
                max(w[1] for w in walls.values())
                - min(w[0] for w in walls.values()), 4)
        report.detections = self._detections(tdir)
        if report.detections:
            ds = [d["detect_s"] for d in report.detections
                  if d.get("detect_s") is not None]
            ms = [d["mttr_s"] for d in report.detections
                  if d.get("mttr_s") is not None]
            if ds:
                report.detect_s_max = round(max(ds), 4)
            if ms:
                report.mttr_s_max = round(max(ms), 4)
        report.kv_keys_final = self.kv.num_keys()
        report.kv_waiters_woken = self.kv.stats.get("waiters_woken", 0)
        report.swept_generations = list(supervisor.kv_gc.swept)
        report.scales_applied = supervisor.scales_applied
        report.scale_generations = sorted(supervisor.scale_generations)
        report.final_workers = supervisor.num_workers
        return report

    def _collect_once(self, agent) -> "tuple[list[float], int] | None":
        """Coordinator-side tree collect: ONE root read; returns
        (per-worker snapshot ages, workers covered)."""
        with elastic.generation_override(self.current_gen):
            rollup = aggregate.collect_rollup_tree(agent, self.tree)
        workers = rollup.get("workers") or {}
        if not workers:
            return None
        now = time.time()
        ages = [now - w["wall"] for w in workers.values()
                if isinstance(w.get("wall"), (int, float))]
        return ages, len(workers)

    def _account_ops(self, report: FleetReport, sup_agent, gc_agent,
                     wall: float):
        by_type: dict[str, int] = {}
        total = 0
        max_agent = 0
        runner = self._runner
        for agent in (runner.agents if runner is not None else []):
            ops = sum(agent.op_counts.values())
            total += ops
            max_agent = max(max_agent, ops)
            for op, cnt in agent.op_counts.items():
                by_type[op] = by_type.get(op, 0) + cnt
        report.worker_ops_total = total
        report.worker_ops_by_type = dict(sorted(by_type.items()))
        report.max_agent_ops = max_agent
        report.supervisor_ops_total = (
            sum(sup_agent.op_counts.values())
            + sum(gc_agent.op_counts.values()))
        denom = max(self.num_workers * self.steps, 1)
        report.ops_per_worker_per_step = round(total / denom, 3)
        report.max_agent_ops_per_step = round(
            max_agent / max(self.steps, 1), 3)
        report.ops_per_sec = round(
            (total + report.supervisor_ops_total) / max(wall, 1e-6), 1)

    def _detections(self, tdir: str) -> "list[dict]":
        """Pair each ``recovery.worker_death`` with the task's actual
        exit instant (detect latency) and the next generation start
        (MTTR) from the supervisor's event log."""
        path = os.path.join(tdir, "events-supervisor.jsonl")
        events = []
        try:
            with open(path) as f:
                for line in f:
                    try:
                        events.append(json.loads(line))
                    except ValueError:
                        continue
        except OSError:
            return []
        runner = self._runner
        out = []
        for i, ev in enumerate(events):
            if ev.get("ev") != "recovery.worker_death":
                continue
            death_wall = ev.get("wall")
            task_id = ev.get("task_id")
            rec = {"kind": ev.get("kind"), "task_id": task_id,
                   "generation": ev.get("generation"),
                   "detect_s": None, "mttr_s": None}
            exit_wall = (runner.exit_wall(task_id)
                         if runner is not None and task_id is not None
                         and task_id >= 0 else None)
            if exit_wall is not None and death_wall is not None \
                    and ev.get("kind") != "stall":
                rec["detect_s"] = round(max(0.0, death_wall - exit_wall),
                                        4)
            elif ev.get("kind") == "stall" and ev.get("detail"):
                # "no heartbeat for X.Xs (budget Ys)": the overage past
                # the budget is the pure detection overhead
                m = _STALL_RE.search(ev["detail"])
                if m:
                    rec["detect_s"] = round(
                        max(0.0, float(m.group(1)) - float(m.group(2))),
                        4)
            if death_wall is not None:
                for later in events[i + 1:]:
                    if later.get("ev") == "recovery.generation_start" \
                            and later.get("wall") is not None:
                        rec["mttr_s"] = round(
                            later["wall"] - death_wall, 4)
                        break
            out.append(rec)
        return out


# ---------------------------------------------------------------------------
# Disaggregated data service: simulated input-worker fleet
# ---------------------------------------------------------------------------

def seeded_data_kill_schedule(seed: int, num_workers: int, *,
                              kills: int = 1,
                              attempt_range: "tuple[int, int]" = (1, 4)
                              ) -> faults.FaultSchedule:
    """Seed-derived input-worker deaths on the ``data.worker_step``
    site: each kill picks a victim and the split-processing ATTEMPT it
    dies on (per-tag hit counter — attempt 1 means the worker dies
    holding a lease it never completed). A pure function of the seed
    (the resilience/faults.py discipline)."""
    rng = random.Random(f"dtx-data-kill:{seed}")
    victims = rng.sample(range(num_workers),
                         k=min(kills, num_workers))
    rules = []
    for victim in victims:
        at = rng.randrange(*attempt_range)
        rules.append(faults.FaultRule(site="data.worker_step",
                                      action="raise",
                                      tag=str(victim), hits=(at,)))
    return faults.FaultSchedule(rules=tuple(rules), seed=seed)


@dataclasses.dataclass
class DataFleetReport:
    """What one DataServiceSim.run measured (bench.py --data-service's
    raw rows + the chaos/property-test observables)."""

    num_workers: int
    num_splits: int
    epochs: int
    wall_s: float
    completed: bool
    #: exactly-once accounting, per epoch: the consumed multiset vs
    #: the expected one
    elements_delivered: int = 0
    expected_elements: int = 0
    duplicate_elements: int = 0
    missing_elements: int = 0
    #: per-epoch sorted element multisets (the property test's object)
    epoch_multisets: list = dataclasses.field(default_factory=list)
    splits_reassigned: int = 0
    workers_died: list = dataclasses.field(default_factory=list)
    elements_per_sec: float = 0.0
    fetch_wait_s: float = 0.0
    splits_per_worker: dict = dataclasses.field(default_factory=dict)
    rollup_workers_seen: int = 0
    rollup_splits_processed: "int | None" = None
    faults_fired: list = dataclasses.field(default_factory=list)
    error: "str | None" = None

    def to_row(self) -> dict:
        row = dataclasses.asdict(self)
        row.pop("epoch_multisets", None)      # big; not a bench field
        return row


class DataServiceSim:
    """N simulated input workers + the real dispatcher/worker/client
    code (input/data_service.py) over one in-memory KV.

    Worker threads run the REAL :class:`~distributed_tensorflow_tpu.
    input.data_service.DataInputWorker` loop; a seeded ``raise`` on
    ``data.worker_step`` kills the thread mid-epoch (its heartbeats
    stop, exactly like a SIGKILL'd input-worker process), and the real
    dispatcher must re-issue the dead worker's leases to survivors.
    The consumer thread drains every epoch through the real
    :class:`DataServiceClient` and the report carries the exactly-once
    accounting (duplicates / missing vs the expected multiset), the
    reassignment count, and per-worker split throughput rolled up
    through the PR 11 tree topology (each worker publishes its own
    metrics registry; the root rollup is collected once at the end).

    ``elements_per_split`` elements are synthesized per FILE split;
    ``work_s`` sleeps that long per split (GIL-releasing — models the
    decode/IO the disaggregation exists to offload).
    """

    def __init__(self, num_workers: int, num_splits: int, *,
                 epochs: int = 1, elements_per_split: int = 4,
                 work_s: float = 0.0, lease_timeout_s: float = 0.5,
                 poll_interval_s: float = 0.01,
                 fault_schedule: "faults.FaultSchedule | None" = None,
                 generation: int = 0, fanout: int = 16,
                 hb_shard_size: int = 32, seed: int = 0,
                 consumer_batch: int = 0,
                 consumer_step_s: float = 0.0,
                 timeout_s: float = 60.0,
                 topology: "DomainTopology | None" = None):
        self.topology = topology
        self.num_workers = num_workers
        self.num_splits = num_splits
        self.epochs = epochs
        self.elements_per_split = elements_per_split
        self.work_s = work_s
        #: trainer-shaped consumer pacing: every ``consumer_batch``
        #: elements cost one ``consumer_step_s`` "train step" (0 =
        #: drain flat out). fetch_wait_s / wall_s is then exactly the
        #: run's infeed-wait fraction — the bench's host-boundedness
        #: observable.
        self.consumer_batch = consumer_batch
        self.consumer_step_s = consumer_step_s
        self.fault_schedule = fault_schedule
        self.generation = generation
        self.tree = aggregate.RollupTopology(num_workers, fanout=fanout)
        self.seed = seed
        self.timeout_s = timeout_s
        self.kv = coordination._LocalService()
        from distributed_tensorflow_tpu.input import data_service as _ds
        from distributed_tensorflow_tpu.input.dataset import Dataset
        from distributed_tensorflow_tpu.input.split_provider import (
            SplitProvider,
        )
        self._ds = _ds
        self.cfg = _ds.DataServiceConfig(
            job=f"sim{seed}", lease_timeout_s=lease_timeout_s,
            poll_interval_s=poll_interval_s,
            hb_shard_size=hb_shard_size, fetch_timeout_s=timeout_s)
        work = self.work_s

        def reader(path):
            idx = int(path.rsplit(":", 1)[1])
            if work:
                time.sleep(work)           # the offloaded decode/IO
            for j in range(self.elements_per_split):
                yield idx * 1_000_000 + j

        files = [f"sim://split:{i}" for i in range(num_splits)]
        self.provider = SplitProvider(
            files, lambda subset: Dataset.from_files(subset, reader),
            seed=seed)

    def expected_multiset(self) -> "list[int]":
        return sorted(s * 1_000_000 + j
                      for s in range(self.num_splits)
                      for j in range(self.elements_per_split))

    def _agent(self, pid: int) -> SimAgent:
        return SimAgent(self.kv, pid, self.num_workers)

    def run(self) -> DataFleetReport:
        n = self.num_workers
        report = DataFleetReport(
            num_workers=n, num_splits=self.num_splits,
            epochs=self.epochs, wall_s=0.0, completed=False,
            expected_elements=(self.num_splits
                               * self.elements_per_split * self.epochs))
        regs = [_registry.MetricsRegistry() for _ in range(n)]
        workers = []
        stop = threading.Event()
        died: dict[int, str] = {}
        died_lock = threading.Lock()

        def worker_main(wid: int):
            with elastic.generation_override(self.generation):
                iw = self._ds.DataInputWorker(
                    self._agent(wid), self.provider, self.cfg,
                    worker_id=wid, num_workers=n, epochs=self.epochs,
                    reg=regs[wid])
                workers.append(iw)
                beats = [0]
                orig_beat = iw.pub.beat

                def beat_and_publish(step):
                    orig_beat(step)
                    beats[0] += 1
                    if beats[0] % 5 == 0:
                        aggregate.publish_snapshot(
                            iw.agent, regs[wid], process_id=wid,
                            seq=beats[0])
                        aggregate.run_duties(iw.agent, self.tree, wid)
                iw.pub.beat = beat_and_publish
                try:
                    iw.run(stop)
                    # final partial so short runs reach the root
                    aggregate.publish_snapshot(iw.agent, regs[wid],
                                               process_id=wid,
                                               seq=beats[0] + 1)
                    aggregate.run_duties(iw.agent, self.tree, wid)
                except faults.FaultInjected as e:
                    with died_lock:
                        died[wid] = str(e)
                except coordination.CoordinationError:
                    with died_lock:
                        died[wid] = "coordination error"

        disp_holder: dict = {}

        def dispatcher_main():
            with elastic.generation_override(self.generation):
                disp = self._ds.DataServiceDispatcher(
                    self._agent(n), self.provider, self.cfg,
                    num_workers=n, epochs=self.epochs,
                    domains=(self.topology.as_map()
                             if self.topology is not None else None))
                disp_holder["disp"] = disp
                while not stop.is_set():
                    try:
                        if not disp.tick():
                            return
                    except faults.FaultInjected:
                        pass            # injected dispatch failure:
                    time.sleep(self.cfg.poll_interval_s)  # next tick

        schedule_cm = (faults.inject(self.fault_schedule)
                       if self.fault_schedule is not None
                       else contextlib.nullcontext())
        t0 = time.time()
        with schedule_cm as registry:
            threads = [threading.Thread(target=worker_main, args=(w,),
                                        daemon=True,
                                        name=f"data-worker-{w}")
                       for w in range(n)]
            dt_thread = threading.Thread(target=dispatcher_main,
                                         daemon=True,
                                         name="data-dispatcher")
            for t in threads:
                t.start()
            dt_thread.start()
            client = None
            try:
                with elastic.generation_override(self.generation):
                    client = self._ds.DataServiceClient(
                        self._agent(n + 1), self.cfg)
                    for e in range(self.epochs):
                        got = []
                        in_batch = 0
                        for el in client.epoch(e):
                            got.append(el)
                            in_batch += 1
                            if self.consumer_batch and \
                                    in_batch >= self.consumer_batch:
                                time.sleep(self.consumer_step_s)
                                in_batch = 0
                        report.epoch_multisets.append(sorted(got))
                    report.completed = True
            except Exception as exc:              # noqa: BLE001
                report.error = f"{type(exc).__name__}: {exc}"
            finally:
                with elastic.generation_override(self.generation):
                    self._ds.signal_shutdown(self._agent(n + 1),
                                             self.cfg)
                stop.set()
                for t in threads:
                    t.join(timeout=5.0)
                dt_thread.join(timeout=5.0)
            report.faults_fired = [
                {"site": s, "tag": t_, "hit": h, "action": a}
                for s, t_, h, a, _ in (registry.events()
                                       if registry is not None else [])]
        report.wall_s = round(time.time() - t0, 3)

        expected = self.expected_multiset()
        delivered = 0
        dup = miss = 0
        for got in report.epoch_multisets:
            delivered += len(got)
            from collections import Counter
            ce, cg = Counter(expected), Counter(got)
            dup += sum((cg - ce).values())
            miss += sum((ce - cg).values())
        report.elements_delivered = delivered
        report.duplicate_elements = dup
        report.missing_elements = miss
        if client is not None:
            report.fetch_wait_s = round(client.total_wait_s, 4)
        report.elements_per_sec = round(
            delivered / max(report.wall_s, 1e-6), 1)
        disp = disp_holder.get("disp")
        if disp is not None:
            report.splits_reassigned = disp.splits_reassigned
        report.workers_died = sorted(died)
        report.splits_per_worker = {
            iw.worker_id: iw.splits_processed for iw in workers}
        # settle sweep (the FleetSim discipline): propagate the final
        # partials to the root deterministically before collecting
        settle_agent = self._agent(n + 2)
        with elastic.generation_override(self.generation):
            for _ in range(self.tree.depth):
                for pid in range(n):
                    aggregate.run_duties(settle_agent, self.tree, pid)
            rollup = aggregate.collect_rollup_tree(settle_agent,
                                                   self.tree)
        workers_seen = rollup.get("workers") or {}
        report.rollup_workers_seen = len(workers_seen)
        splits_metric = (rollup.get("metrics") or {}).get(
            "data/splits_processed")
        if isinstance(splits_metric, dict) and \
                isinstance(splits_metric.get("sum"), (int, float)):
            report.rollup_splits_processed = int(splits_metric["sum"])
        return report
