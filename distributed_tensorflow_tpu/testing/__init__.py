"""Test infrastructure: strategy conformance, virtual devices, combos.

≙ the reference's distribute test toolkit (SURVEY.md §4):
strategy_test_lib.py (behavior contract), strategy_combinations.py
(canned strategies), test_util.set_logical_devices_to_at_least (virtual
devices — here the 8-device CPU mesh from tests/conftest.py).
"""

from distributed_tensorflow_tpu.testing.strategy_conformance import (  # noqa: F401
    StrategyConformance)
from distributed_tensorflow_tpu.testing import multi_process_runner  # noqa: F401
