"""Multi-process test infrastructure: run test fns in real processes.

TPU-native equivalent of the reference's MultiProcessRunner
(reference: tensorflow/python/distribute/multi_process_runner.py:107 —
fork-per-task with TF_CONFIG injection, stdout capture, process kill,
return-value collection) and multi_worker_test_base.py:123 (in-process
cluster creation). Differences by design:

- Tasks are ``multiprocessing`` *spawn* processes (a fresh interpreter:
  no inherited JAX backend state — the analogue of the reference's
  _ProcFunc re-exec), not forks of a TF runtime.
- The cluster's "server" is the TSL coordination service started by
  ``jax.distributed.initialize`` inside task (worker, 0); there is no
  grpc worker server to start (SURVEY.md §2.7 mapping).
- CPU backend with gloo cross-process collectives stands in for DCN.

Usage::

    def worker_fn():
        runtime = bootstrap.initialize()           # reads TF_CONFIG
        ...
        return jax.process_index()

    result = multi_process_runner.run(worker_fn, num_workers=2)
    assert result.return_values == [0, 1]

Test fns must be module-level (picklable by reference) since spawn
re-imports the defining module in the child.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import multiprocessing
import os
import pickle
import socket
import sys
import time
import traceback
from typing import Any, Callable, Mapping, Sequence

_MP = multiprocessing.get_context("spawn")


def pick_unused_port() -> int:
    """Reserve an ephemeral localhost port and release it for the task."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def create_cluster_spec(num_workers: int = 1, num_ps: int = 0,
                        has_chief: bool = False,
                        has_evaluator: bool = False) -> dict[str, list[str]]:
    """≙ multi_worker_test_base.create_cluster_spec: localhost addresses
    with freshly picked ports."""
    spec: dict[str, list[str]] = {}
    if has_chief:
        spec["chief"] = [f"127.0.0.1:{pick_unused_port()}"]
    if num_workers:
        spec["worker"] = [f"127.0.0.1:{pick_unused_port()}"
                          for _ in range(num_workers)]
    if num_ps:
        spec["ps"] = [f"127.0.0.1:{pick_unused_port()}"
                      for _ in range(num_ps)]
    if has_evaluator:
        spec["evaluator"] = [f"127.0.0.1:{pick_unused_port()}"]
    return spec


@dataclasses.dataclass
class TaskResult:
    task_type: str
    task_id: int
    exitcode: int | None
    value: Any = None
    error: str | None = None
    stdout: str = ""


@dataclasses.dataclass
class MultiProcessRunnerResult:
    """≙ the reference's MultiProcessRunnerResult (return_value, stdout)."""
    tasks: dict[tuple[str, int], TaskResult]

    @property
    def return_values(self) -> list[Any]:
        return [t.value for t in self._ordered() if t.error is None
                and t.exitcode == 0]

    @property
    def stdout(self) -> list[str]:
        return [t.stdout for t in self._ordered()]

    def _ordered(self) -> list[TaskResult]:
        return [self.tasks[k] for k in sorted(self.tasks)]


class UnexpectedSubprocessExitError(RuntimeError):
    """A task died without reporting a result (crash / external kill)."""

    def __init__(self, msg: str, result: MultiProcessRunnerResult):
        super().__init__(msg)
        self.mpr_result = result


class SubprocessError(RuntimeError):
    """A task raised; carries the child traceback."""

    def __init__(self, msg: str, result: MultiProcessRunnerResult):
        super().__init__(msg)
        self.mpr_result = result


def _child_main(env: dict, payload: bytes, task_type: str, task_id: int,
                conn, stdout_path: str):
    """Spawn-process entry. Sets env BEFORE unpickling the user fn (which
    imports its defining module, and typically jax)."""
    os.environ.update(env)
    # Capture this task's stdout/stderr to a file the parent reads back
    # (≙ multi_process_runner's per-task log capture).
    sys.stdout.flush(); sys.stderr.flush()
    out_f = open(stdout_path, "w", buffering=1)
    os.dup2(out_f.fileno(), 1)
    os.dup2(out_f.fileno(), 2)
    try:
        import jax
        jax.config.update("jax_platforms",
                          env.get("JAX_PLATFORMS", "cpu"))
        with contextlib.suppress(Exception):
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        fn, args, kwargs = pickle.loads(payload)
        value = fn(*args, **kwargs)
        try:
            conn.send(("ok", value))
        except Exception:
            conn.send(("ok", repr(value)))   # unpicklable return value
        exitcode = 0
    except BaseException:
        conn.send(("error", traceback.format_exc()))
        exitcode = 1
    with contextlib.suppress(Exception):
        conn.close()
    out_f.flush()
    # Skip interpreter teardown: a dead peer can leave the coordination
    # client's shutdown path hanging, and atexit hooks must not wedge the
    # harness (≙ multi_process_runner's _ProcFunc sys.exit discipline).
    os._exit(exitcode)


class MultiProcessRunner:
    """Run ``fn`` once per cluster task in separate spawn processes.

    ≙ multi_process_runner.MultiProcessRunner(:107): TF_CONFIG is
    injected per task; the worker-0 address doubles as the coordination
    service (jax.distributed coordinator). ``terminate`` SIGKILLs a task
    for fault-tolerance tests (:646 ``terminate``), and ``join`` collects
    return values / re-raises child failures.
    """

    def __init__(self, fn: Callable, cluster_spec: Mapping[str, Sequence[str]],
                 *, args: tuple = (), kwargs: dict | None = None,
                 env: Mapping[str, str] | None = None,
                 devices_per_process: int = 1,
                 init_jax_distributed: bool = False,
                 timeout: float = 300.0):
        self._fn = fn
        self._spec = {k: list(v) for k, v in cluster_spec.items()}
        self._args = args
        self._kwargs = kwargs or {}
        self._extra_env = dict(env or {})
        self._devices = devices_per_process
        self._init_jax = init_jax_distributed
        self._timeout = timeout
        self._procs: dict[tuple[str, int], Any] = {}
        self._conns: dict[tuple[str, int], Any] = {}
        self._stdout: dict[tuple[str, int], str] = {}
        self._results: dict[tuple[str, int], TaskResult] = {}
        self._tmpdir = None

    # -- lifecycle --------------------------------------------------------
    def start(self):
        import tempfile
        self._tmpdir = tempfile.mkdtemp(prefix="mpr_")
        payload = pickle.dumps((self._fn, self._args, self._kwargs))
        ntasks = sum(len(v) for v in self._spec.values())
        task_index = 0
        for task_type in sorted(self._spec):
            for task_id, _ in enumerate(self._spec[task_type]):
                env = dict(os.environ)
                env.update({
                    "TF_CONFIG": json.dumps({
                        "cluster": self._spec,
                        "task": {"type": task_type, "index": task_id},
                    }),
                    "JAX_PLATFORMS": "cpu",
                    "XLA_FLAGS": (
                        env.get("XLA_FLAGS", "").replace(
                            "--xla_force_host_platform_device_count=8", "")
                        + f" --xla_force_host_platform_device_count="
                          f"{self._devices}"),
                    "DTX_MPR_NUM_TASKS": str(ntasks),
                    "DTX_MPR_TASK_INDEX": str(task_index),
                })
                env.update(self._extra_env)
                parent_conn, child_conn = _MP.Pipe()
                stdout_path = os.path.join(
                    self._tmpdir, f"{task_type}_{task_id}.out")
                p = _MP.Process(
                    target=_child_main,
                    args=(env, payload, task_type, task_id, child_conn,
                          stdout_path),
                    daemon=True)
                p.start()
                child_conn.close()
                key = (task_type, task_id)
                self._procs[key] = p
                self._conns[key] = parent_conn
                self._stdout[key] = stdout_path
                task_index += 1
        return self

    def terminate(self, task_type: str, task_id: int):
        """SIGKILL one task (≙ multi_process_runner.terminate :646)."""
        p = self._procs[(task_type, task_id)]
        p.kill()
        p.join(10)

    def terminate_all(self):
        for p in self._procs.values():
            if p.is_alive():
                p.kill()
        for p in self._procs.values():
            p.join(5)

    def join(self, timeout: float | None = None,
             raise_on_error: bool = True) -> MultiProcessRunnerResult:
        deadline = time.monotonic() + (timeout or self._timeout)
        pending = dict(self._procs)
        while pending and time.monotonic() < deadline:
            for key, p in list(pending.items()):
                p.join(0.05)
                if p.exitcode is not None:
                    self._collect(key)
                    del pending[key]
        if pending:
            for key in pending:
                self._collect(key, timed_out=True)
            self.terminate_all()
            result = MultiProcessRunnerResult(dict(self._results))
            raise UnexpectedSubprocessExitError(
                f"tasks {sorted(pending)} did not exit within "
                f"{timeout or self._timeout}s; stdout:\n"
                + self._format_logs(pending), result)

        result = MultiProcessRunnerResult(dict(self._results))
        if raise_on_error:
            errors = {k: t for k, t in self._results.items()
                      if t.error is not None}
            if errors:
                k = sorted(errors)[0]
                raise SubprocessError(
                    f"task {k} raised:\n{errors[k].error}", result)
            # exit 1 is only "expected" when _child_main actually
            # delivered the error; a task that died before reporting
            # (spawn bootstrap failure, broken pipe) must raise.
            crashed = {k: t for k, t in self._results.items()
                       if t.exitcode != 0 and t.error is None}
            if crashed:
                raise UnexpectedSubprocessExitError(
                    f"tasks {sorted(crashed)} exited abnormally "
                    f"({ {k: t.exitcode for k, t in crashed.items()} }); "
                    f"stdout:\n" + self._format_logs(crashed), result)
        return result

    def _collect(self, key, timed_out: bool = False):
        if key in self._results:
            return
        p = self._procs[key]
        conn = self._conns[key]
        value, error = None, None
        if conn.poll(0 if not timed_out else 0.1):
            try:
                status, data = conn.recv()
                if status == "ok":
                    value = data
                else:
                    error = data
            except (EOFError, OSError):
                pass
        stdout = ""
        path = self._stdout.get(key)
        if path and os.path.exists(path):
            with open(path, errors="replace") as f:
                stdout = f.read()
        self._results[key] = TaskResult(
            task_type=key[0], task_id=key[1], exitcode=p.exitcode,
            value=value, error=error, stdout=stdout)

    def _format_logs(self, keys) -> str:
        parts = []
        for key in sorted(keys):
            self._collect(key)
            t = self._results[key]
            parts.append(f"--- {key} (exit {t.exitcode}) ---\n"
                         f"{t.stdout[-2000:]}")
        return "\n".join(parts)


def run(fn: Callable, *, num_workers: int = 2, num_ps: int = 0,
        has_chief: bool = False, has_evaluator: bool = False,
        args: tuple = (), kwargs: dict | None = None,
        env: Mapping[str, str] | None = None, devices_per_process: int = 1,
        timeout: float = 300.0) -> MultiProcessRunnerResult:
    """One-call form (≙ multi_process_runner.run :1332): build a localhost
    cluster spec, start every task, join, return results."""
    spec = create_cluster_spec(num_workers=num_workers, num_ps=num_ps,
                               has_chief=has_chief,
                               has_evaluator=has_evaluator)
    runner = MultiProcessRunner(
        fn, spec, args=args, kwargs=kwargs, env=env,
        devices_per_process=devices_per_process, timeout=timeout)
    runner.start()
    try:
        return runner.join(timeout)
    finally:
        runner.terminate_all()
