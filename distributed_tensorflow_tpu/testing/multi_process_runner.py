"""Multi-process test infrastructure: run test fns in real processes.

TPU-native equivalent of the reference's MultiProcessRunner
(reference: tensorflow/python/distribute/multi_process_runner.py:107 —
fork-per-task with TF_CONFIG injection, stdout capture, process kill,
return-value collection) and multi_worker_test_base.py:123 (in-process
cluster creation). Differences by design:

- Tasks are ``multiprocessing`` *spawn* processes (a fresh interpreter:
  no inherited JAX backend state — the analogue of the reference's
  _ProcFunc re-exec), not forks of a TF runtime.
- The cluster's "server" is the TSL coordination service started by
  ``jax.distributed.initialize`` inside task (worker, 0); there is no
  grpc worker server to start (SURVEY.md §2.7 mapping).
- CPU backend with gloo cross-process collectives stands in for DCN.

Usage::

    def worker_fn():
        runtime = bootstrap.initialize()           # reads TF_CONFIG
        ...
        return jax.process_index()

    result = multi_process_runner.run(worker_fn, num_workers=2)
    assert result.return_values == [0, 1]

Test fns must be module-level (picklable by reference) since spawn
re-imports the defining module in the child.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import multiprocessing
import os
import pickle
import socket
import sys
import time
import traceback
from typing import Any, Callable, Mapping, Sequence

_MP = multiprocessing.get_context("spawn")


def pick_unused_port() -> int:
    """Reserve an ephemeral localhost port and release it for the task."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def create_cluster_spec(num_workers: int = 1, num_ps: int = 0,
                        has_chief: bool = False,
                        has_evaluator: bool = False) -> dict[str, list[str]]:
    """≙ multi_worker_test_base.create_cluster_spec: localhost addresses
    with freshly picked ports."""
    spec: dict[str, list[str]] = {}
    if has_chief:
        spec["chief"] = [f"127.0.0.1:{pick_unused_port()}"]
    if num_workers:
        spec["worker"] = [f"127.0.0.1:{pick_unused_port()}"
                          for _ in range(num_workers)]
    if num_ps:
        spec["ps"] = [f"127.0.0.1:{pick_unused_port()}"
                      for _ in range(num_ps)]
    if has_evaluator:
        spec["evaluator"] = [f"127.0.0.1:{pick_unused_port()}"]
    return spec


def _child_env(devices_per_process: int) -> dict[str, str]:
    """Child-process env for a CPU-backed cluster task: force the CPU
    platform and exactly ``devices_per_process`` host devices (scrubbing
    any forced count inherited from the parent's XLA_FLAGS, e.g.
    conftest's =8)."""
    env = dict(os.environ)
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count="
                 f"{devices_per_process}")
    env.update({"JAX_PLATFORMS": "cpu", "XLA_FLAGS": " ".join(flags)})
    return env


@dataclasses.dataclass
class TaskResult:
    task_type: str
    task_id: int
    exitcode: int | None
    value: Any = None
    error: str | None = None
    stdout: str = ""


@dataclasses.dataclass
class MultiProcessRunnerResult:
    """≙ the reference's MultiProcessRunnerResult (return_value, stdout)."""
    tasks: dict[tuple[str, int], TaskResult]

    @property
    def return_values(self) -> list[Any]:
        return [t.value for t in self._ordered() if t.error is None
                and t.exitcode == 0]

    @property
    def stdout(self) -> list[str]:
        return [t.stdout for t in self._ordered()]

    def _ordered(self) -> list[TaskResult]:
        return [self.tasks[k] for k in sorted(self.tasks)]


class UnexpectedSubprocessExitError(RuntimeError):
    """A task died without reporting a result (crash / external kill)."""

    def __init__(self, msg: str, result: MultiProcessRunnerResult):
        super().__init__(msg)
        self.mpr_result = result


class SubprocessError(RuntimeError):
    """A task raised; carries the child traceback."""

    def __init__(self, msg: str, result: MultiProcessRunnerResult):
        super().__init__(msg)
        self.mpr_result = result


def _child_main(env: dict, payload: bytes, task_type: str, task_id: int,
                conn, stdout_path: str):
    """Spawn-process entry. Sets env BEFORE unpickling the user fn (which
    imports its defining module, and typically jax)."""
    os.environ.update(env)
    # Capture this task's stdout/stderr to a file the parent reads back
    # (≙ multi_process_runner's per-task log capture).
    sys.stdout.flush(); sys.stderr.flush()
    out_f = open(stdout_path, "w", buffering=1)
    os.dup2(out_f.fileno(), 1)
    os.dup2(out_f.fileno(), 2)
    try:
        import jax
        jax.config.update("jax_platforms",
                          env.get("JAX_PLATFORMS", "cpu"))
        if task_type != "evaluator":
            # gloo stands in for DCN on the CPU backend — but ONLY for
            # tasks that join the distributed world. The evaluator runs
            # in its own single-task world by design (≙ the reference's
            # sidecar evaluator), never calls jax.distributed.initialize,
            # and on jaxlib<=0.4.36 building a gloo-configured CPU client
            # with no distributed client is a hard TypeError
            # (make_gloo_tcp_collectives rejects distributed_client=None).
            with contextlib.suppress(Exception):
                jax.config.update("jax_cpu_collectives_implementation",
                                  "gloo")
        fn, args, kwargs = pickle.loads(payload)
        value = fn(*args, **kwargs)
        try:
            conn.send(("ok", value))
        except Exception:
            conn.send(("ok", repr(value)))   # unpicklable return value
        exitcode = 0
    except SystemExit as e:
        # Preserve platform exit codes: a task exiting SystemExit(42)
        # (the preemption-restart convention, failure_handling.py) must
        # surface 42 to a supervising parent, not a generic 1 — the
        # recovery supervisor classifies failures by exit code.
        exitcode = e.code if isinstance(e.code, int) else \
            (0 if e.code is None else 1)
        if exitcode == 0:
            conn.send(("ok", None))
        else:
            conn.send(("error", f"SystemExit({e.code})"))
    except BaseException:
        conn.send(("error", traceback.format_exc()))
        exitcode = 1
    with contextlib.suppress(Exception):
        conn.close()
    out_f.flush()
    # Skip interpreter teardown: a dead peer can leave the coordination
    # client's shutdown path hanging, and atexit hooks must not wedge the
    # harness (≙ multi_process_runner's _ProcFunc sys.exit discipline).
    os._exit(exitcode)


class MultiProcessRunner:
    """Run ``fn`` once per cluster task in separate spawn processes.

    ≙ multi_process_runner.MultiProcessRunner(:107): TF_CONFIG is
    injected per task; the worker-0 address doubles as the coordination
    service (jax.distributed coordinator). ``terminate`` SIGKILLs a task
    for fault-tolerance tests (:646 ``terminate``), and ``join`` collects
    return values / re-raises child failures.
    """

    def __init__(self, fn: Callable, cluster_spec: Mapping[str, Sequence[str]],
                 *, args: tuple = (), kwargs: dict | None = None,
                 env: Mapping[str, str] | None = None,
                 devices_per_process: int = 1,
                 init_jax_distributed: bool = False,
                 timeout: float = 300.0):
        self._fn = fn
        self._spec = {k: list(v) for k, v in cluster_spec.items()}
        self._args = args
        self._kwargs = kwargs or {}
        self._extra_env = dict(env or {})
        self._devices = devices_per_process
        self._init_jax = init_jax_distributed
        self._timeout = timeout
        self._procs: dict[tuple[str, int], Any] = {}
        self._conns: dict[tuple[str, int], Any] = {}
        self._stdout: dict[tuple[str, int], str] = {}
        self._results: dict[tuple[str, int], TaskResult] = {}
        self._task_env: dict[tuple[str, int], dict] = {}
        self._incarnation: dict[tuple[str, int], int] = {}
        #: TaskResults of dead incarnations replaced by :meth:`restart`
        #: (a supervisor's failure-history raw material).
        self.history: list[TaskResult] = []
        self._payload: bytes | None = None
        self._tmpdir = None

    # -- lifecycle --------------------------------------------------------
    def _task_keys(self) -> list[tuple[str, int]]:
        return [(t, i) for t in sorted(self._spec)
                for i in range(len(self._spec[t]))]

    @property
    def num_tasks(self) -> int:
        return sum(len(v) for v in self._spec.values())

    def _base_env(self, task_type: str, task_id: int,
                  task_index: int) -> dict:
        env = _child_env(self._devices)
        env.update({
            "TF_CONFIG": json.dumps({
                "cluster": self._spec,
                "task": {"type": task_type, "index": task_id},
            }),
            "DTX_MPR_NUM_TASKS": str(self.num_tasks),
            "DTX_MPR_TASK_INDEX": str(task_index),
        })
        env.update(self._extra_env)
        return env

    def _spawn(self, key: tuple[str, int], env: dict):
        """(Re)spawn one task process with ``env``; replaces any previous
        pipe/stdout bookkeeping for ``key``."""
        task_type, task_id = key
        inc = self._incarnation.get(key, 0)
        parent_conn, child_conn = _MP.Pipe()
        stdout_path = os.path.join(
            self._tmpdir,
            f"{task_type}_{task_id}.out" if inc == 0
            else f"{task_type}_{task_id}.r{inc}.out")
        p = _MP.Process(
            target=_child_main,
            args=(env, self._payload, task_type, task_id, child_conn,
                  stdout_path),
            daemon=True)
        p.start()
        child_conn.close()
        self._procs[key] = p
        self._conns[key] = parent_conn
        self._stdout[key] = stdout_path
        self._task_env[key] = env
        self._incarnation[key] = inc + 1

    def start(self):
        import tempfile
        self._tmpdir = tempfile.mkdtemp(prefix="mpr_")
        self._payload = pickle.dumps((self._fn, self._args, self._kwargs))
        for task_index, key in enumerate(self._task_keys()):
            self._spawn(key, self._base_env(key[0], key[1], task_index))
        return self

    def restart(self, task_type: str, task_id: int, *,
                env: Mapping[str, str] | None = None):
        """Per-worker restart: SIGKILL the task if still alive, archive
        its result into :attr:`history`, and respawn it with its prior
        environment plus ``env`` overrides (e.g. a fresh ``TF_CONFIG``
        or a bumped ``DTX_CLUSTER_GENERATION``). ``join`` then waits on
        the NEW incarnation."""
        key = (task_type, task_id)
        p = self._procs[key]
        if p.exitcode is None:
            p.kill()
            p.join(10)
        self._collect(key)
        self.history.append(self._results.pop(key))
        new_env = dict(self._task_env[key])
        new_env.update(env or {})
        self._spawn(key, new_env)

    def reform(self, cluster_spec: Mapping[str, Sequence[str]] | None = None,
               *, env: Mapping[str, str] | None = None,
               allow_resize: bool = False):
        """Full-cluster restart: kill every task, swap in a fresh cluster
        spec (fresh coordination-service ports — required: the dead
        incarnation's service socket may linger in TIME_WAIT), and
        respawn all tasks via :meth:`restart` with the new ``TF_CONFIG``
        plus ``env`` overrides. The recovery supervisor's reform
        primitive.

        ``allow_resize=True`` lets the new spec change the cluster
        shape (topology-elastic reform): dropped task slots are
        archived into :attr:`history`, new slots are spawned fresh, and
        every task's ``DTX_MPR_NUM_TASKS``/``DTX_MPR_TASK_INDEX`` are
        re-derived from the new spec."""
        self.terminate_all()
        if cluster_spec is not None:
            new = {k: list(v) for k, v in cluster_spec.items()}
            if sorted((t, len(v)) for t, v in new.items()) != \
                    sorted((t, len(v)) for t, v in self._spec.items()):
                if not allow_resize:
                    raise ValueError(
                        f"reform must keep the cluster shape: "
                        f"{self._spec.keys()} -> {new.keys()}")
                old_keys = set(self._task_keys())
                self._spec = new
                for key in sorted(old_keys - set(self._task_keys())):
                    # dropped slot: archive its last incarnation
                    self._collect(key)
                    self.history.append(self._results.pop(key))
                    self._procs.pop(key, None)
                    self._conns.pop(key, None)
                    self._stdout.pop(key, None)
                    self._task_env.pop(key, None)
            else:
                self._spec = new
        ntasks = self.num_tasks
        for task_index, key in enumerate(self._task_keys()):
            updates = {
                "TF_CONFIG": json.dumps({
                    "cluster": self._spec,
                    "task": {"type": key[0], "index": key[1]},
                }),
                "DTX_MPR_NUM_TASKS": str(ntasks),
                "DTX_MPR_TASK_INDEX": str(task_index),
            }
            updates.update(env or {})
            if key in self._procs:
                self.restart(key[0], key[1], env=updates)
            else:                         # grown slot: fresh spawn
                new_env = self._base_env(key[0], key[1], task_index)
                new_env.update(updates)
                self._spawn(key, new_env)

    def poll(self) -> dict[tuple[str, int], int]:
        """Exit codes of tasks whose current incarnation has exited
        (non-blocking; a restarted-and-running task is absent)."""
        return {k: p.exitcode for k, p in self._procs.items()
                if p.exitcode is not None}

    def alive_tasks(self) -> list[tuple[str, int]]:
        return sorted(k for k, p in self._procs.items()
                      if p.exitcode is None)

    def terminate(self, task_type: str, task_id: int):
        """SIGKILL one task (≙ multi_process_runner.terminate :646)."""
        p = self._procs[(task_type, task_id)]
        p.kill()
        p.join(10)

    def terminate_all(self):
        for p in self._procs.values():
            if p.is_alive():
                p.kill()
        for p in self._procs.values():
            p.join(5)

    def join(self, timeout: float | None = None,
             raise_on_error: bool = True) -> MultiProcessRunnerResult:
        deadline = time.monotonic() + (timeout or self._timeout)
        pending = dict(self._procs)
        while pending and time.monotonic() < deadline:
            for key, p in list(pending.items()):
                p.join(0.05)
                if p.exitcode is not None:
                    self._collect(key)
                    del pending[key]
        if pending:
            for key in pending:
                self._collect(key, timed_out=True)
            self.terminate_all()
            result = MultiProcessRunnerResult(dict(self._results))
            raise UnexpectedSubprocessExitError(
                f"tasks {sorted(pending)} did not exit within "
                f"{timeout or self._timeout}s; stdout:\n"
                + self._format_logs(pending), result)

        result = MultiProcessRunnerResult(dict(self._results))
        if raise_on_error:
            errors = {k: t for k, t in self._results.items()
                      if t.error is not None}
            if errors:
                k = sorted(errors)[0]
                raise SubprocessError(
                    f"task {k} raised:\n{errors[k].error}", result)
            # exit 1 is only "expected" when _child_main actually
            # delivered the error; a task that died before reporting
            # (spawn bootstrap failure, broken pipe) must raise.
            crashed = {k: t for k, t in self._results.items()
                       if t.exitcode != 0 and t.error is None}
            if crashed:
                raise UnexpectedSubprocessExitError(
                    f"tasks {sorted(crashed)} exited abnormally "
                    f"({ {k: t.exitcode for k, t in crashed.items()} }); "
                    f"stdout:\n" + self._format_logs(crashed), result)
        return result

    def _collect(self, key, timed_out: bool = False):
        if key in self._results:
            return
        p = self._procs[key]
        conn = self._conns[key]
        value, error = None, None
        if conn.poll(0 if not timed_out else 0.1):
            try:
                status, data = conn.recv()
                if status == "ok":
                    value = data
                else:
                    error = data
            except (EOFError, OSError):
                pass
        stdout = ""
        path = self._stdout.get(key)
        if path and os.path.exists(path):
            with open(path, errors="replace") as f:
                stdout = f.read()
        self._results[key] = TaskResult(
            task_type=key[0], task_id=key[1], exitcode=p.exitcode,
            value=value, error=error, stdout=stdout)

    def _format_logs(self, keys) -> str:
        parts = []
        for key in sorted(keys):
            self._collect(key)
            t = self._results[key]
            parts.append(f"--- {key} (exit {t.exitcode}) ---\n"
                         f"{t.stdout[-2000:]}")
        return "\n".join(parts)


def run(fn: Callable, *, num_workers: int = 2, num_ps: int = 0,
        has_chief: bool = False, has_evaluator: bool = False,
        args: tuple = (), kwargs: dict | None = None,
        env: Mapping[str, str] | None = None, devices_per_process: int = 1,
        timeout: float = 300.0) -> MultiProcessRunnerResult:
    """One-call form (≙ multi_process_runner.run :1332): build a localhost
    cluster spec, start every task, join, return results."""
    spec = create_cluster_spec(num_workers=num_workers, num_ps=num_ps,
                               has_chief=has_chief,
                               has_evaluator=has_evaluator)
    runner = MultiProcessRunner(
        fn, spec, args=args, kwargs=kwargs, env=env,
        devices_per_process=devices_per_process, timeout=timeout)
    runner.start()
    try:
        return runner.join(timeout)
    finally:
        runner.terminate_all()


# ---------------------------------------------------------------------------
# Pool runner: persistent task processes amortizing spawn + jax import
# ---------------------------------------------------------------------------

_POOL_TASK_DIED = "pool task died without reporting"

def _pool_task_cleanup():
    """Reset per-task process state between pooled runs.

    Every pooled run gets a FRESH cluster (new coordination-service ports
    in a fresh TF_CONFIG), so between runs the child must disconnect from
    the old service and drop the backends built against it; the next
    run's ``bootstrap.initialize`` then rebuilds both. Framework
    singletons that cache cluster facts are reset the same way.
    """
    import contextlib

    import jax

    with contextlib.suppress(Exception):
        from distributed_tensorflow_tpu.cluster import bootstrap
        bootstrap.shutdown()
    with contextlib.suppress(Exception):
        if jax._src.distributed.global_state.client is not None:
            jax.distributed.shutdown()
    with contextlib.suppress(Exception):
        from jax._src import xla_bridge
        xla_bridge._clear_backends()
    jax.clear_caches()
    with contextlib.suppress(Exception):
        from distributed_tensorflow_tpu.cluster import coordination
        coordination._LOCAL._kv.clear()
        coordination._LOCAL._barriers.clear()
    with contextlib.suppress(Exception):
        # A coordinator's generation is per-cluster-incarnation state:
        # carrying it into the next pooled run would skip publishing
        # current_gen on the NEW coordination service and strand every
        # worker loop.
        import sys as _sys
        rd = _sys.modules.get(
            "distributed_tensorflow_tpu.coordinator.remote_dispatch")
        if rd is not None:
            rd._reset_generation_for_tests()


def _pool_child_main(base_env: dict, conn, ready_path: str):
    """Persistent pool-task entry: import jax ONCE, then serve tasks.

    Protocol (one message per task): recv ``(env_updates, stdout_path,
    payload)``; run; send ``("ok", value)`` / ``("error", traceback)``.
    A ``None`` message shuts the process down.
    ≙ multi_process_runner.MultiProcessPoolRunner's _pool_runner_worker
    (reference multi_process_runner.py:902,1000) — persistent workers
    pulling closures off a pipe instead of re-spawning per test.
    """
    os.environ.update(base_env)
    sys.stdout.flush(); sys.stderr.flush()
    import jax
    jax.config.update("jax_platforms", base_env.get("JAX_PLATFORMS", "cpu"))
    with contextlib.suppress(Exception):
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    with open(ready_path, "w") as f:
        f.write("ready")
    while True:
        try:
            msg = conn.recv()
        except (EOFError, OSError):
            break
        if msg is None:
            break
        env_updates, stdout_path, payload = msg
        out_f = open(stdout_path, "w", buffering=1)
        os.dup2(out_f.fileno(), 1)
        os.dup2(out_f.fileno(), 2)
        # Hermeticity: restore every env key this run touches, so a
        # caller-supplied ``env`` can't leak into later pooled runs.
        env_saved = {k: os.environ.get(k) for k in env_updates}
        try:
            os.environ.update(env_updates)
            fn, args, kwargs = pickle.loads(payload)
            value = fn(*args, **kwargs)
            try:
                conn.send(("ok", value))
            except Exception:
                conn.send(("ok", repr(value)))
        except BaseException:
            conn.send(("error", traceback.format_exc()))
        finally:
            try:
                _pool_task_cleanup()
            except BaseException:
                pass
            for k, old in env_saved.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old
            out_f.flush()
            out_f.close()       # fds 1/2 keep their dup'd descriptors
    os._exit(0)


class MultiProcessPoolRunner:
    """A persistent pool of cluster-task processes.

    ≙ multi_process_runner.MultiProcessPoolRunner (reference
    multi_process_runner.py:902): tests share long-lived task processes
    so each test pays pipe round-trips instead of process spawn + jax
    import (the dominant cost of a multi-process suite on a small CI
    box). Unlike the reference's pool — which keeps ONE cluster alive
    across tests — every :meth:`run` here builds a fresh localhost
    cluster spec (fresh coordination-service ports), so tests stay
    hermetic: no KV/barrier-name leakage between tests.

    Tasks that are killed mid-test (fault-injection) must keep using
    :class:`MultiProcessRunner`; a pool child that dies marks the pool
    broken and the next ``run`` transparently restarts it.
    """

    def __init__(self, *, num_workers: int = 2, num_ps: int = 0,
                 has_chief: bool = False, has_evaluator: bool = False,
                 devices_per_process: int = 1,
                 env: Mapping[str, str] | None = None):
        self._shape = dict(num_workers=num_workers, num_ps=num_ps,
                           has_chief=has_chief, has_evaluator=has_evaluator)
        self._devices = devices_per_process
        self._extra_env = dict(env or {})
        self._procs: dict[tuple[str, int], Any] = {}
        self._conns: dict[tuple[str, int], Any] = {}
        self._tmpdir = None
        self._run_seq = 0

    # -- lifecycle --------------------------------------------------------
    @property
    def started(self) -> bool:
        return bool(self._procs)

    def _task_keys(self) -> list[tuple[str, int]]:
        spec = create_cluster_spec(**self._shape)
        return [(t, i) for t in sorted(spec) for i in range(len(spec[t]))]

    def start(self, timeout: float = 120.0):
        import tempfile
        self.shutdown()
        self._tmpdir = tempfile.mkdtemp(prefix="mpp_")
        ready_paths = {}
        for key in self._task_keys():
            env = _child_env(self._devices)
            env.update(self._extra_env)
            parent_conn, child_conn = _MP.Pipe()
            ready = os.path.join(self._tmpdir,
                                 f"ready_{key[0]}_{key[1]}")
            p = _MP.Process(target=_pool_child_main,
                            args=(env, child_conn, ready), daemon=True)
            p.start()
            child_conn.close()
            self._procs[key] = p
            self._conns[key] = parent_conn
            ready_paths[key] = ready
        deadline = time.monotonic() + timeout
        for key, path in ready_paths.items():
            while not os.path.exists(path):
                if time.monotonic() > deadline:
                    self.shutdown()
                    raise RuntimeError(
                        f"pool task {key} failed to come up in {timeout}s")
                if self._procs[key].exitcode is not None:
                    self.shutdown()
                    raise RuntimeError(
                        f"pool task {key} died during startup")
                time.sleep(0.05)
        return self

    def shutdown(self):
        for conn in self._conns.values():
            with contextlib.suppress(Exception):
                conn.send(None)
        for p in self._procs.values():
            p.join(5)
            if p.is_alive():
                p.kill()
                p.join(5)
        self._procs.clear()
        self._conns.clear()

    # -- dispatch ---------------------------------------------------------
    def run(self, fn: Callable, *, args: tuple = (),
            kwargs: dict | None = None,
            env: Mapping[str, str] | None = None,
            timeout: float = 300.0,
            raise_on_error: bool = True) -> MultiProcessRunnerResult:
        """Run ``fn`` once per cluster task on the pooled processes.

        Same contract as module-level :func:`run`, minus process-kill
        support. A fresh cluster spec (fresh ports) is generated per
        call; TF_CONFIG is re-injected through the task pipe.
        """
        if not self.started:
            self.start()
        elif any(p.exitcode is not None for p in self._procs.values()):
            # A child died while the pool was idle (OOM-kill, crash in
            # cleanup): restart transparently, as the class contract says.
            self.start()
        self._run_seq += 1
        spec = create_cluster_spec(**self._shape)
        payload = pickle.dumps((fn, args, kwargs or {}))
        ntasks = sum(len(v) for v in spec.values())
        stdout_paths: dict[tuple[str, int], str] = {}
        task_index = 0
        for task_type in sorted(spec):
            for task_id in range(len(spec[task_type])):
                key = (task_type, task_id)
                env_updates = {
                    "TF_CONFIG": json.dumps({
                        "cluster": spec,
                        "task": {"type": task_type, "index": task_id},
                    }),
                    "DTX_MPR_NUM_TASKS": str(ntasks),
                    "DTX_MPR_TASK_INDEX": str(task_index),
                }
                env_updates.update(env or {})
                stdout_path = os.path.join(
                    self._tmpdir,
                    f"run{self._run_seq}_{task_type}_{task_id}.out")
                stdout_paths[key] = stdout_path
                self._conns[key].send(
                    (env_updates, stdout_path, payload))
                task_index += 1

        results: dict[tuple[str, int], TaskResult] = {}
        deadline = time.monotonic() + timeout
        pending = dict(self._conns)
        broken = False
        while pending and time.monotonic() < deadline:
            for key, conn in list(pending.items()):
                value, error, got = None, None, False
                if conn.poll(0.05):
                    try:
                        status, data = conn.recv()
                        got = True
                        if status == "ok":
                            value = data
                        else:
                            error = data
                    except (EOFError, OSError):
                        got, error, broken = True, _POOL_TASK_DIED, True
                elif self._procs[key].exitcode is not None:
                    got, error, broken = True, _POOL_TASK_DIED, True
                if got:
                    stdout = ""
                    path = stdout_paths[key]
                    if os.path.exists(path):
                        with open(path, errors="replace") as f:
                            stdout = f.read()
                    results[key] = TaskResult(
                        task_type=key[0], task_id=key[1],
                        exitcode=self._procs[key].exitcode or 0,
                        value=value, error=error, stdout=stdout)
                    del pending[key]
        if pending or broken:
            self.shutdown()      # next run restarts cleanly
            if pending:
                raise UnexpectedSubprocessExitError(
                    f"pooled tasks {sorted(pending)} did not report within "
                    f"{timeout}s (pool restarted)",
                    MultiProcessRunnerResult(results))
        result = MultiProcessRunnerResult(results)
        if raise_on_error:
            # Same exception split as MultiProcessRunner.join: a task
            # that RAISED -> SubprocessError (with traceback); a task
            # that DIED without reporting -> UnexpectedSubprocessExitError.
            crashed = {k: t for k, t in results.items()
                       if t.error == _POOL_TASK_DIED}
            if crashed:
                raise UnexpectedSubprocessExitError(
                    f"pooled tasks {sorted(crashed)} died without "
                    f"reporting (pool restarted)", result)
            errors = {k: t for k, t in results.items()
                      if t.error is not None}
            if errors:
                k = sorted(errors)[0]
                raise SubprocessError(
                    f"pooled task {k} raised:\n{errors[k].error}", result)
        return result
