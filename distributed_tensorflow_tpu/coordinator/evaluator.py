"""Sidecar evaluator task + ``train_and_evaluate`` orchestration.

≙ the Estimator-era continuous-evaluation orchestration the reference
runs through ``run_distribute_coordinator``
(tensorflow/python/distribute/distribute_coordinator.py:627 — the
"evaluator" task runs eval in its own single-task world while
chief/workers train) and the keras sidecar evaluator
(tf_keras SidecarEvaluator: watch a checkpoint directory, evaluate every
new checkpoint, write summaries, stop at a final step).

TPU-native shape: the evaluator is a process OUTSIDE the SPMD world — it
never joins ``jax.distributed`` (the trainers' collectives must not wait
on it) and sees training progress only through the checkpoint directory,
whose index-last commit protocol (checkpoint/checkpoint.py) guarantees
it can only observe complete checkpoints.
"""

from __future__ import annotations

import os
import re
import time
from typing import Any, Callable

from distributed_tensorflow_tpu.checkpoint.checkpoint import (
    Checkpoint,
    latest_checkpoint,
)


class SidecarEvaluator:
    """Continuously evaluate every new checkpoint in a directory.

    ``eval_fn(checkpoint, step) -> dict[str, float]`` runs after the
    checkpoint is restored in place; returned metrics are written as TB
    scalars to ``summary_dir`` (utils/summary.py — real event files).

    Stops when a checkpoint with number >= ``final_step`` has been
    evaluated (≙ the reference stopping at the final checkpoint), or
    after ``idle_timeout_s`` with nothing new (trainer died).
    """

    def __init__(self, checkpoint: Checkpoint, directory: str,
                 eval_fn: Callable[[Checkpoint, int], dict],
                 *, checkpoint_name: str = "ckpt",
                 summary_dir: str | None = None,
                 poll_interval_s: float = 0.5,
                 final_step: int | None = None,
                 idle_timeout_s: float = 120.0):
        self._checkpoint = checkpoint
        self._directory = directory
        self._eval_fn = eval_fn
        self._name = checkpoint_name
        self._summary_dir = summary_dir
        self._poll_s = poll_interval_s
        self._final_step = final_step
        self._idle_timeout_s = idle_timeout_s

    @staticmethod
    def _step_of(path: str) -> int:
        m = re.search(r"-(\d+)$", path)
        return int(m.group(1)) if m else -1

    def run(self) -> list[tuple[int, dict]]:
        """The evaluator loop; returns [(step, metrics), ...] evaluated."""
        writer = None
        if self._summary_dir is not None:
            from distributed_tensorflow_tpu.utils.summary import (
                SummaryWriter)
            writer = SummaryWriter(self._summary_dir,
                                   filename_suffix=".eval")
        evaluated: list[tuple[int, dict]] = []
        seen: set[str] = set()
        deadline = time.monotonic() + self._idle_timeout_s
        try:
            while True:
                path = latest_checkpoint(self._directory, self._name)
                if path is not None and path not in seen:
                    seen.add(path)
                    step = self._step_of(path)
                    try:
                        restored = self._checkpoint.restore(path)
                    except (OSError, KeyError, ValueError):
                        # rotation race: the trainer swept this
                        # checkpoint mid-restore — skip it, the next
                        # poll sees a newer one (tf_keras
                        # SidecarEvaluator tolerates this the same way)
                        continue
                    # restore() assigns variables in place but returns
                    # plain leaves; fold top-level ones back into the
                    # checkpoint so eval_fn sees the restored state
                    for name, val in restored.items():
                        obj = self._checkpoint._objects.get(name)
                        if obj is not None and not hasattr(obj, "assign"):
                            self._checkpoint._objects[name] = val
                    metrics = self._eval_fn(self._checkpoint, step) or {}
                    if writer is not None:
                        writer.scalars(
                            {f"eval/{k}": float(v)
                             for k, v in metrics.items()}, step)
                        writer.flush()
                    evaluated.append((step, metrics))
                    deadline = time.monotonic() + self._idle_timeout_s
                    if (self._final_step is not None
                            and step >= self._final_step):
                        return evaluated
                elif time.monotonic() > deadline:
                    return evaluated          # trainer gone quiet: stop
                else:
                    time.sleep(self._poll_s)
        finally:
            if writer is not None:
                writer.close()


def train_and_evaluate(train_fn: Callable, eval_fn: Callable, strategy,
                       cluster_spec=None, task_type: str | None = None,
                       task_id: int | None = None) -> Any:
    """Role dispatch for ported ``tf.estimator.train_and_evaluate``
    scripts (≙ distribute_coordinator.py:627 orchestration): every task
    calls this with its own TF_CONFIG; chief/worker tasks run
    ``train_fn(context)`` inside the connected SPMD world, the
    ``evaluator`` task runs ``eval_fn(context)`` in its own single-task
    world WITHOUT joining the distributed runtime.

    Both callbacks receive a ``WorkerContext``; the evaluator's context
    has ``task_type == "evaluator"`` and typically constructs a
    :class:`SidecarEvaluator` over the shared checkpoint directory.
    """
    from distributed_tensorflow_tpu.cluster.resolver import (
        ClusterSpec, EVALUATOR, SimpleClusterResolver,
        TFConfigClusterResolver)
    from distributed_tensorflow_tpu.coordinator.distribute_coordinator \
        import WorkerContext, run_distribute_coordinator

    if isinstance(cluster_spec, dict):
        cluster_spec = ClusterSpec(cluster_spec)
    if cluster_spec is None:
        resolver = TFConfigClusterResolver()
        cluster_spec = resolver.cluster_spec()
        task_type = task_type or resolver.task_type
        task_id = task_id if task_id is not None else resolver.task_id

    if task_type == EVALUATOR:
        ctx = WorkerContext(strategy, cluster_spec, task_type, task_id)
        return eval_fn(ctx)
    return run_distribute_coordinator(
        train_fn, strategy, cluster_spec=cluster_spec,
        task_type=task_type, task_id=task_id)
