"""Sidecar evaluator task + ``train_and_evaluate`` orchestration.

≙ the Estimator-era continuous-evaluation orchestration the reference
runs through ``run_distribute_coordinator``
(tensorflow/python/distribute/distribute_coordinator.py:627 — the
"evaluator" task runs eval in its own single-task world while
chief/workers train) and the keras sidecar evaluator
(tf_keras SidecarEvaluator: watch a checkpoint directory, evaluate every
new checkpoint, write summaries, stop at a final step).

TPU-native shape: the evaluator is a process OUTSIDE the SPMD world — it
never joins ``jax.distributed`` (the trainers' collectives must not wait
on it) and sees training progress only through the checkpoint directory,
whose index-last commit protocol (checkpoint/checkpoint.py) guarantees
it can only observe complete checkpoints.
"""

from __future__ import annotations

import logging
import os
import re
import time
from typing import Any, Callable

from distributed_tensorflow_tpu.checkpoint.checkpoint import (
    Checkpoint,
    CheckpointCorruptError,
    latest_checkpoint,
)

_log = logging.getLogger(__name__)


class SidecarEvaluator:
    """Continuously evaluate every new checkpoint in a directory.

    ``eval_fn(checkpoint, step) -> dict[str, float]`` runs after the
    checkpoint is restored in place; returned metrics are written as TB
    scalars to ``summary_dir`` (utils/summary.py — real event files).

    Stops when a checkpoint with number >= ``final_step`` has been
    evaluated (≙ the reference stopping at the final checkpoint), or
    after ``idle_timeout_s`` with nothing new (trainer died).

    ``evaluate_every_checkpoint=True`` walks EVERY unseen checkpoint in
    step order instead of only the latest — for evaluators slower than
    the trainer's rotation cadence that must not skip steps.
    """

    def __init__(self, checkpoint: Checkpoint, directory: str,
                 eval_fn: Callable[[Checkpoint, int], dict],
                 *, checkpoint_name: str = "ckpt",
                 summary_dir: str | None = None,
                 poll_interval_s: float = 0.5,
                 final_step: int | None = None,
                 idle_timeout_s: float = 120.0,
                 evaluate_every_checkpoint: bool = False):
        self._checkpoint = checkpoint
        self._directory = directory
        self._eval_fn = eval_fn
        self._name = checkpoint_name
        self._summary_dir = summary_dir
        self._poll_s = poll_interval_s
        self._final_step = final_step
        self._idle_timeout_s = idle_timeout_s
        self._eval_all = evaluate_every_checkpoint

    @staticmethod
    def _step_of(path: str) -> int:
        """Checkpoint number from a ``<name>-<number>`` path; raises on
        an unparseable name — a silent -1 would quietly disable the
        ``final_step`` stop condition and leave the loop exiting only
        via idle timeout."""
        m = re.search(r"-(\d+)$", path)
        if not m:
            raise ValueError(
                f"checkpoint path {path!r} does not end in "
                f"'-<number>'; cannot order it / match final_step")
        return int(m.group(1))

    def _pending_paths(self, seen: set) -> list:
        """Unseen checkpoints to evaluate, oldest first (or just the
        latest when evaluate_every_checkpoint=False)."""
        if not self._eval_all:
            path = latest_checkpoint(self._directory, self._name)
            return [path] if path is not None and path not in seen else []
        from distributed_tensorflow_tpu.checkpoint.checkpoint import (
            _INDEX_FILE)
        pat = re.compile(re.escape(self._name) + r"-(\d+)$")
        found = []
        try:
            names = os.listdir(self._directory)
        except OSError:
            return []
        for n in names:
            m = pat.match(n)
            full = os.path.join(self._directory, n)
            # the index file is the COMMIT MARKER (written last by
            # _commit): a dir without it is a checkpoint mid-write —
            # listing it would mark it seen and permanently skip it
            if (m and full not in seen
                    and os.path.exists(os.path.join(full, _INDEX_FILE))):
                found.append((int(m.group(1)), full))
        return [p for _, p in sorted(found)]

    def run(self) -> list[tuple[int, dict]]:
        """The evaluator loop; returns [(step, metrics), ...] evaluated."""
        writer = None
        if self._summary_dir is not None:
            from distributed_tensorflow_tpu.utils.summary import (
                SummaryWriter)
            writer = SummaryWriter(self._summary_dir,
                                   filename_suffix=".eval")
        evaluated: list[tuple[int, dict]] = []
        seen: set[str] = set()
        deadline = time.monotonic() + self._idle_timeout_s
        try:
            while True:
                progressed = False
                for path in self._pending_paths(seen):
                    seen.add(path)
                    # paths come from the name-(\d+) pattern, so this
                    # cannot fail here; _step_of stays strict for any
                    # external caller (a silent -1 would disable the
                    # final_step stop)
                    step = self._step_of(path)
                    try:
                        self._checkpoint.restore_into(path)
                    except (OSError, KeyError, ValueError,
                            CheckpointCorruptError):
                        # rotation race or torn shard: the trainer swept
                        # (or half-wrote) this checkpoint — skip it, the
                        # next poll sees a newer one (tf_keras
                        # SidecarEvaluator tolerates this the same way)
                        _log.info(
                            "SidecarEvaluator: checkpoint %r vanished "
                            "mid-restore (rotation race); skipping",
                            path)
                        continue
                    metrics = self._eval_fn(self._checkpoint, step) or {}
                    if writer is not None:
                        writer.scalars(
                            {f"eval/{k}": float(v)
                             for k, v in metrics.items()}, step)
                        writer.flush()
                    evaluated.append((step, metrics))
                    progressed = True
                    deadline = time.monotonic() + self._idle_timeout_s
                    if (self._final_step is not None
                            and step >= self._final_step):
                        return evaluated
                if not progressed:
                    if time.monotonic() > deadline:
                        return evaluated      # trainer gone quiet: stop
                    time.sleep(self._poll_s)
        finally:
            if writer is not None:
                writer.close()


def train_and_evaluate(train_fn: Callable, eval_fn: Callable, strategy,
                       cluster_spec=None, task_type: str | None = None,
                       task_id: int | None = None) -> Any:
    """Role dispatch for ported ``tf.estimator.train_and_evaluate``
    scripts (≙ distribute_coordinator.py:627 orchestration): every task
    calls this with its own TF_CONFIG; chief/worker tasks run
    ``train_fn(context)`` inside the connected SPMD world, the
    ``evaluator`` task runs ``eval_fn(context)`` in its own single-task
    world WITHOUT joining the distributed runtime.

    Both callbacks receive a ``WorkerContext``; the evaluator's context
    has ``task_type == "evaluator"`` and typically constructs a
    :class:`SidecarEvaluator` over the shared checkpoint directory.
    """
    from distributed_tensorflow_tpu.cluster.resolver import (
        ClusterSpec, EVALUATOR, SimpleClusterResolver,
        TFConfigClusterResolver)
    from distributed_tensorflow_tpu.coordinator.distribute_coordinator \
        import WorkerContext, run_distribute_coordinator

    if isinstance(cluster_spec, dict):
        cluster_spec = ClusterSpec(cluster_spec)
    if cluster_spec is None:
        resolver = TFConfigClusterResolver()
        cluster_spec = resolver.cluster_spec()
        task_type = task_type or resolver.task_type
        task_id = task_id if task_id is not None else resolver.task_id

    if task_type == EVALUATOR:
        ctx = WorkerContext(strategy, cluster_spec, task_type, task_id)
        return eval_fn(ctx)
    return run_distribute_coordinator(
        train_fn, strategy, cluster_spec=cluster_spec,
        task_type=task_type, task_id=task_id)
