"""ClusterCoordinator: closure queue + per-worker dispatch threads.

TPU-native counterpart of tensorflow/python/distribute/coordinator/
cluster_coordinator.py (SURVEY.md §2.5, §3.3):

- ``ClusterCoordinator``        ≙ :1399 — ``schedule``/``join``/``fetch``
- ``Closure``                   ≙ :193  — a scheduled fn + its RemoteValue
- ``_CoordinatedClosureQueue``  ≙ :322  — bounded queue, put_back on worker
  failure, error propagation, cancellation on application error
- ``Worker``                    ≙ :1027 — one dispatch thread per worker
- ``Cluster``                   ≙ :1247
- ``RemoteValue``/``PerWorkerValues`` ≙ remote_value.py / values.py

Redesign note: the reference dispatches closures to remote *processes* over
the grpc eager service; worker failure shows up as grpc UnavailableError and
is retried (``WorkerPreemptionHandler.wait_on_failure``, :879), PS failure
surfaces as ``PSUnavailableError`` (:130) for user-level restore. Here a
"worker" is a dispatch lane bound to a local accelerator (or a remote host
in the multi-process runtime); the same queue/retry semantics apply with
``WorkerPreemptionError`` as the retryable class. The asynchrony — the
actual point of PS training — is identical: no global barrier, workers pull
independently.
"""

from __future__ import annotations

import contextlib
import enum
import threading
import traceback
from typing import Any, Callable, Sequence

import jax

from distributed_tensorflow_tpu import telemetry
from distributed_tensorflow_tpu.coordinator import metric_utils
from distributed_tensorflow_tpu.coordinator.watchdog import WatchDog
from distributed_tensorflow_tpu.resilience import faults
from distributed_tensorflow_tpu.resilience.health import WorkerHealthTracker
from distributed_tensorflow_tpu.resilience.retry import RetryPolicy


class WorkerPreemptionError(RuntimeError):
    """Retryable worker failure (≙ grpc UnavailableError in the reference:
    the closure is re-queued and run on another worker)."""


class PSUnavailableError(RuntimeError):
    """Parameter-server state lost (≙ cluster_coordinator.py:130): not
    retryable — user restores from checkpoint."""


class ClosureCancelledError(RuntimeError):
    pass


class _Status(enum.Enum):
    PENDING = "pending"
    READY = "ready"
    ABORTED = "aborted"
    CANCELLED = "cancelled"


class RemoteValue:
    """Future for a scheduled closure's result (≙ remote_value.py)."""

    def __init__(self):
        self._status = _Status.PENDING
        self._value = None
        self._error: BaseException | None = None
        self._cv = threading.Condition()

    def _set_value(self, value):
        with self._cv:
            self._value = value
            self._status = _Status.READY
            self._cv.notify_all()

    def _set_error(self, err: BaseException):
        with self._cv:
            self._error = err
            self._status = _Status.ABORTED
            self._cv.notify_all()

    def _cancel(self):
        with self._cv:
            if self._status is _Status.PENDING:
                self._status = _Status.CANCELLED
                self._cv.notify_all()

    def fetch(self, timeout: float | None = None):
        """Block until ready; raises the closure's error if it failed."""
        with self._cv:
            self._cv.wait_for(
                lambda: self._status is not _Status.PENDING, timeout)
            if self._status is _Status.PENDING:
                raise TimeoutError("RemoteValue not ready")
            if self._status is _Status.CANCELLED:
                raise ClosureCancelledError("closure cancelled")
            if self._status is _Status.ABORTED:
                raise self._error
            return self._value

    get = fetch


class PerWorkerValues:
    """One value per worker (≙ coordinator/values.py PerWorkerValues)."""

    def __init__(self, values: Sequence):
        self._values = tuple(values)

    @property
    def values(self) -> tuple:
        return self._values

    def __getitem__(self, i):
        return self._values[i]

    def __len__(self):
        return len(self._values)


class Closure:
    """A schedulable unit (≙ cluster_coordinator.py:193)."""

    def __init__(self, fn: Callable, args, kwargs):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs or {}
        self.output = RemoteValue()

    def _resolved(self, worker: "Worker"):
        def resolve(v):
            return v.values[worker.worker_index] \
                if isinstance(v, PerWorkerValues) else v

        args = jax.tree_util.tree_map(
            resolve, self.args,
            is_leaf=lambda v: isinstance(v, PerWorkerValues))
        kwargs = jax.tree_util.tree_map(
            resolve, self.kwargs,
            is_leaf=lambda v: isinstance(v, PerWorkerValues))
        return args, kwargs

    def execute_on(self, worker: "Worker"):
        args, kwargs = self._resolved(worker)
        with worker.device_scope():
            result = self.fn(*args, **kwargs)
        self.output._set_value(result)

    def execute_remote(self, worker: "Worker"):
        """Ship to the worker's remote process (≙ the grpc dispatch in
        cluster_coordinator.py:1027); WorkerPreemptionError propagates to
        the caller for transparent re-queue."""
        args, kwargs = self._resolved(worker)
        result = worker.lane.execute(self.fn, args, kwargs)
        self.output._set_value(result)

    def mark_cancelled(self):
        self.output._cancel()


class _CoordinatedClosureQueue:
    """Bounded closure queue with failure semantics
    (≙ cluster_coordinator.py:322).

    - ``put``/``get`` with backpressure
    - ``put_back`` returns an in-flight closure after a retryable worker
      failure (≙ :514)
    - ``mark_failed`` records an application error: the queue cancels all
      pending closures and re-raises from ``wait``/``put``
    """

    def __init__(self, max_pending: int = 1024):
        self._queue: list[Closure] = []
        self._inflight = 0
        self._error: BaseException | None = None
        self._cancelled = False
        self._max = max_pending
        self._cv = threading.Condition()
        self.closures_queued = metric_utils.Counter("closures_queued_total")
        self.closures_done = metric_utils.Counter("closures_done_total")
        self._gauge_queued = None       # attach_gauges wires these to the
        self._gauge_inflight = None     # CoordinatorMetrics gauge cells

    def attach_gauges(self, queued: "metric_utils.Gauge",
                      inflight: "metric_utils.Gauge"):
        """Wire the queued/inflight CoordinatorMetrics cells to this
        queue's live depth (read by snapshots/fleet rollups)."""
        with self._cv:
            self._gauge_queued = queued
            self._gauge_inflight = inflight
            self._update_gauges_locked()

    def _update_gauges_locked(self):
        if self._gauge_queued is not None:
            self._gauge_queued.set(len(self._queue))
        if self._gauge_inflight is not None:
            self._gauge_inflight.set(self._inflight)

    def _raise_if_error(self):
        if self._error is not None:
            err, self._error = self._error, None
            self._cancelled = False
            raise err

    def put(self, closure: Closure):
        with self._cv:
            self._raise_if_error()
            self._cv.wait_for(lambda: len(self._queue) < self._max
                              or self._error is not None)
            self._raise_if_error()
            self._queue.append(closure)
            self.closures_queued.increment()
            self._update_gauges_locked()
            self._cv.notify_all()

    def get(self, timeout: float | None = None) -> Closure | None:
        with self._cv:
            # Block on work arriving; a cancelled-and-drained queue must
            # still wait out the timeout (not spin hot in worker threads).
            self._cv.wait_for(lambda: bool(self._queue), timeout)
            if not self._queue:
                return None
            closure = self._queue.pop(0)
            self._inflight += 1
            self._update_gauges_locked()
            self._cv.notify_all()
            return closure

    def put_back(self, closure: Closure):
        with self._cv:
            self._inflight -= 1
            if self._cancelled:
                closure.mark_cancelled()
            else:
                self._queue.insert(0, closure)
            self._update_gauges_locked()
            self._cv.notify_all()

    def mark_finished(self, closure: Closure):
        with self._cv:
            self._inflight -= 1
            self.closures_done.increment()
            self._update_gauges_locked()
            self._cv.notify_all()

    def mark_failed(self, err: BaseException):
        with self._cv:
            self._error = err
            self._cancelled = True
            for c in self._queue:
                c.mark_cancelled()
            self._queue.clear()
            self._update_gauges_locked()
            self._cv.notify_all()

    def wait(self, timeout: float | None = None) -> bool:
        """Block until queue drained and nothing in flight."""
        with self._cv:
            done = self._cv.wait_for(
                lambda: (not self._queue and self._inflight == 0)
                or self._error is not None, timeout)
            self._raise_if_error()
            return done

    def done(self) -> bool:
        with self._cv:
            self._raise_if_error()
            return not self._queue and self._inflight == 0

    def stop(self):
        with self._cv:
            self._cancelled = True
            self._cv.notify_all()


class Worker:
    """One dispatch lane (≙ cluster_coordinator.py:1027): a thread pulling
    closures and executing them against this worker's device — or, with a
    ``lane``, shipping them to a remote worker PROCESS over the
    coordination-service transport (coordinator/remote_dispatch.py)."""

    def __init__(self, worker_index: int, cluster: "Cluster", device=None,
                 lane=None):
        self.worker_index = worker_index
        self.cluster = cluster
        self.device = device
        self.lane = lane
        self.failures = 0
        self._stop = threading.Event()
        self.thread = threading.Thread(
            target=self._process_queue, daemon=True,
            name=f"dtx-worker-{worker_index}")
        self.thread.start()

    @contextlib.contextmanager
    def device_scope(self):
        if self.device is not None:
            with jax.default_device(self.device):
                yield
        else:
            yield

    def _process_queue(self):
        # ≙ Worker._process_queue (:1173)
        queue = self.cluster.closure_queue
        health = self.cluster.health
        while not self._stop.is_set():
            if health.is_quarantined(self.worker_index):
                # benched after repeated failures: leave queued closures
                # to healthy lanes until the quarantine window expires
                self._stop.wait(0.1)
                continue
            if self.lane is not None and not self.lane.alive():
                # dead remote worker: don't pull work this lane can't run
                # (≙ wait_on_failure backoff, :879); resumes if the
                # worker process is restarted and heartbeats again.
                self._stop.wait(0.5)
                continue
            closure = queue.get(timeout=0.2)
            if closure is None:
                continue
            self._process_closure(closure, queue)

    def _process_closure(self, closure: Closure, queue):
        try:
            with self.cluster.coordinator_metrics.closure_execution.time():
                faults.fire(
                    "closure.execute", tag=self.worker_index,
                    exc=WorkerPreemptionError,
                    msg=f"injected preemption on worker {self.worker_index}")
                if self.lane is not None:
                    closure.execute_remote(self)
                else:
                    closure.execute_on(self)
            queue.mark_finished(closure)
            self.cluster.health.record_success(self.worker_index)
        except WorkerPreemptionError as e:
            # ≙ WorkerPreemptionHandler.wait_on_failure (:879): transparent
            # retry on another worker; this lane backs off (and is
            # quarantined by the health tracker if it keeps failing)
            self.failures += 1
            self.cluster.health.record_failure(self.worker_index)
            telemetry.counter("coordinator/dispatch_retries",
                              "closures re-queued after worker "
                              "preemption").increment()
            telemetry.event("dispatch.retry", worker=self.worker_index,
                            error=str(e)[:200])
            queue.put_back(closure)
        except PSUnavailableError as e:
            closure.output._set_error(e)
            telemetry.event("dispatch.failure", worker=self.worker_index,
                            kind="ps_unavailable", error=str(e)[:200])
            queue.mark_failed(e)
        except BaseException as e:  # application error -> surface to user
            closure.output._set_error(e)
            telemetry.event("dispatch.failure", worker=self.worker_index,
                            kind=type(e).__name__, error=str(e)[:200])
            queue.mark_failed(e)

    def stop(self):
        self._stop.set()


class Cluster:
    """Owns workers + the closure queue (≙ cluster_coordinator.py:1247).

    ``remote_worker_ids``: process ids of remote worker tasks (each
    running ``remote_dispatch.run_worker_loop``); lanes then dispatch
    across processes instead of local devices."""

    def __init__(self, num_workers: int, devices=None,
                 remote_worker_ids: Sequence[int] | None = None,
                 health: WorkerHealthTracker | None = None):
        self.closure_queue = _CoordinatedClosureQueue()
        self.coordinator_metrics = metric_utils.CoordinatorMetrics()
        self.closure_queue.attach_gauges(self.coordinator_metrics.queued,
                                         self.coordinator_metrics.inflight)
        self.health = health or WorkerHealthTracker()
        n = (len(remote_worker_ids) if remote_worker_ids is not None
             else num_workers)
        for i in range(n):
            self.health.register(i)
        if remote_worker_ids is not None:
            from distributed_tensorflow_tpu.coordinator.remote_dispatch \
                import RemoteLane
            self.workers = [
                Worker(i, self, lane=RemoteLane(pid))
                for i, pid in enumerate(remote_worker_ids)]
            return
        if devices is None:
            local = jax.local_devices()
            devices = [local[i % len(local)] for i in range(num_workers)]
        self.workers = [Worker(i, self, devices[i])
                        for i in range(num_workers)]

    def schedule(self, fn, args, kwargs) -> RemoteValue:
        closure = Closure(fn, args, kwargs)
        self.closure_queue.put(closure)
        return closure.output

    def join(self, timeout=None):
        self.closure_queue.wait(timeout)

    def done(self) -> bool:
        return self.closure_queue.done()

    def stop(self):
        self.closure_queue.stop()
        for w in self.workers:
            w.stop()


class _IteratorBuilder:
    """Picklable zero-arg factory rebuilding a worker-side iterator
    (attached to the handle so a restarted worker self-heals)."""

    def __init__(self, dataset_fn):
        self.dataset_fn = dataset_fn

    def __call__(self):
        return iter(self.dataset_fn())


def _create_worker_iterator(dataset_fn):
    """Runs ON the worker (via remote dispatch): build the dataset there
    and register the live iterator, returning an opaque handle."""
    from distributed_tensorflow_tpu.coordinator.remote_dispatch import (
        current_worker_service)
    service = current_worker_service()
    builder = _IteratorBuilder(dataset_fn)
    return service.create_resource(builder, builder=builder)


def _create_worker_resource(resource_fn):
    """Runs ON the worker: build and register an arbitrary per-worker
    resource (resource_fn itself is the rebuild factory)."""
    from distributed_tensorflow_tpu.coordinator.remote_dispatch import (
        current_worker_service)
    return current_worker_service().create_resource(
        resource_fn, builder=resource_fn)


class ClusterCoordinator:
    """Async training driver (≙ cluster_coordinator.py:1399).

    ``schedule`` enqueues ``fn`` for any free worker and returns a
    ``RemoteValue``; ``join`` blocks until all scheduled closures ran.
    Worker preemption is retried transparently; application errors cancel
    the queue and re-raise at ``schedule``/``join`` — exactly the reference
    contract.
    """

    def __init__(self, strategy=None, num_workers: int | None = None,
                 devices=None, watchdog_timeout: float = 300.0,
                 remote_worker_ids: Sequence[int] | None = None,
                 health: WorkerHealthTracker | None = None):
        self.strategy = strategy
        if num_workers is None:
            resolver = getattr(strategy, "cluster_resolver", None)
            if resolver is not None and resolver.cluster_spec():
                num_workers = resolver.cluster_spec().num_tasks("worker") or 1
            else:
                num_workers = len(jax.local_devices())
        if remote_worker_ids is not None:
            num_workers = len(remote_worker_ids)
        self.cluster = Cluster(num_workers, devices,
                               remote_worker_ids=remote_worker_ids,
                               health=health)
        self._per_worker_resources: list = []
        self._watchdog = WatchDog(timeout=watchdog_timeout)

    @property
    def num_workers(self) -> int:
        return len(self.cluster.workers)

    def schedule(self, fn: Callable, args=(), kwargs=None) -> RemoteValue:
        self._watchdog.report_activity()
        return self.cluster.schedule(fn, args, kwargs)

    def join(self, timeout: float | None = None):
        self._watchdog.report_activity()
        self.cluster.join(timeout)

    def done(self) -> bool:
        return self.cluster.done()

    def worker_restarted(self, worker_id: int):
        """Tell the dispatch layer a worker's PROCESS was restarted by a
        recovery supervisor (new cluster generation): the lane's
        quarantine and failure streak no longer describe the fresh
        process, so it goes straight back into rotation instead of
        sitting out a quarantine window it inherited from its dead
        predecessor."""
        self.cluster.health.worker_restarted(worker_id)
        from distributed_tensorflow_tpu.telemetry import events as _events
        _events.event("dispatch.worker_restarted", worker=worker_id)

    def fetch(self, values, timeout: float | None = None):
        """Fetch RemoteValue(s) (structure-preserving)."""
        return jax.tree_util.tree_map(
            lambda v: v.fetch(timeout) if isinstance(v, RemoteValue) else v,
            values, is_leaf=lambda v: isinstance(v, RemoteValue))

    def create_per_worker_dataset(self, dataset_fn: Callable) -> PerWorkerValues:
        """≙ create_per_worker_dataset (:1604): one iterator per worker.

        With remote lanes the iterator is created ON each worker process
        (the reference's semantics — worker-side datasets feed
        worker-side steps without shipping data through the
        coordinator); closures receive an opaque handle that resolves to
        the live iterator inside the worker (remote_dispatch
        resource registry). Local lanes keep coordinator-side iterators.
        """
        if any(w.lane is not None for w in self.cluster.workers):
            return PerWorkerValues(self._create_on_workers(
                _create_worker_iterator, (dataset_fn,)))
        return PerWorkerValues([iter(dataset_fn())
                                for _ in range(self.num_workers)])

    def _create_on_workers(self, fn, args, *, attempts: int = 3,
                           timeout_s: float = 120.0) -> list:
        """Fan a pinned closure out to EVERY worker lane in parallel
        (publish all tasks, then gather), retrying per worker on
        preemption under the shared RetryPolicy — the transparent-retry
        contract, pinned rather than re-routed (per-worker resources
        belong to a specific worker)."""
        policy = RetryPolicy(max_attempts=attempts,
                             retryable=(WorkerPreemptionError,))
        lanes = [w.lane for w in self.cluster.workers]
        seqs = [lane.submit(fn, args, {}) for lane in lanes]
        results: list = [None] * len(lanes)
        for i, (lane, seq) in enumerate(zip(lanes, seqs)):
            pending = {"seq": seq}

            def gather(lane=lane, pending=pending):
                return lane.wait(pending["seq"], timeout_s=timeout_s)

            def resubmit(exc, attempt, lane=lane, pending=pending):
                # worker may come back: publish the task again
                pending["seq"] = lane.submit(fn, args, {})

            try:
                results[i] = policy.call(gather, on_retry=resubmit)
            except WorkerPreemptionError as e:
                raise WorkerPreemptionError(
                    f"worker {lane.worker_id} unavailable after "
                    f"{attempts} attempts creating a per-worker "
                    f"resource") from e
        return results

    def create_per_worker_resource(self, resource_fn: Callable) -> PerWorkerValues:
        """One resource per worker; with remote lanes the object is
        created and lives ON the worker process (closures get a
        self-healing handle), like per-worker datasets."""
        if any(w.lane is not None for w in self.cluster.workers):
            vals = PerWorkerValues(self._create_on_workers(
                _create_worker_resource, (resource_fn,)))
        else:
            vals = PerWorkerValues([resource_fn()
                                    for _ in range(self.num_workers)])
        self._per_worker_resources.append(vals)
        return vals

    def shutdown(self):
        lanes = [w.lane for w in self.cluster.workers if w.lane is not None]
        if lanes:
            from distributed_tensorflow_tpu.coordinator.remote_dispatch \
                import shutdown_workers
            # only wait on acks from workers that are still alive — a
            # killed worker would otherwise stall shutdown to the timeout
            shutdown_workers(
                worker_ids=[l.worker_id for l in lanes if l.alive()])
        self.cluster.stop()
        self._watchdog.stop()
