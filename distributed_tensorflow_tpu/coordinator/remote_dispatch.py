"""Remote closure dispatch: coordinator -> worker processes.

TPU-native counterpart of the reference's remote execution path in
tensorflow/python/distribute/coordinator/cluster_coordinator.py:1027
(``Worker`` — one grpc-backed remote executor per worker process) and
:879 (``WorkerPreemptionHandler.wait_on_failure`` — grpc UnavailableError
from a dead worker triggers transparent re-dispatch).

The reference's transport is the grpc eager service; the TPU-native
control plane is the TSL coordination service that every process is
already connected to (cluster/coordination.py), so closures ride its KV
store:

    coordinator                           worker process w
    -----------                           ----------------
    task/<w>/<seq>  <- pickle(fn,args)    blocking get task/<w>/<seq>
    poll result/<w>/<seq> ------------->  run fn
      | heartbeat stale?                  set result/<w>/<seq>
      v                                   seq += 1
    WorkerPreemptionError -> re-queue

Death detection is organic: each worker service bumps a heartbeat key a
few times a second; a coordinator lane that stops seeing bumps while
waiting raises ``WorkerPreemptionError`` — the producer the retry
machinery in cluster_coordinator.py needs. This is a CONTROL plane: data
(model state) moves inside SPMD programs over ICI/DCN, not through the
KV store.
"""

from __future__ import annotations

import pickle
import threading
import time
import traceback
from typing import Any, Callable

from distributed_tensorflow_tpu.cluster.coordination import (
    CoordinationServiceAgent,
    coordination_service,
)

_PREFIX = "dtx_coord"
_HEARTBEAT_INTERVAL = 0.2


class RemoteClosureError(RuntimeError):
    """The closure raised on the worker; carries the remote traceback."""


def _hb_key(worker_id: int) -> str:
    return f"{_PREFIX}/hb/{worker_id}"


def _task_key(worker_id: int, seq: int) -> str:
    return f"{_PREFIX}/task/{worker_id}/{seq}"


def _result_key(worker_id: int, seq: int) -> str:
    return f"{_PREFIX}/result/{worker_id}/{seq}"


def _shutdown_key() -> str:
    return f"{_PREFIX}/shutdown"


class RemoteLane:
    """Coordinator-side handle to one worker process (≙ the grpc channel
    + remote executor inside cluster_coordinator.Worker :1027)."""

    def __init__(self, worker_id: int,
                 agent: CoordinationServiceAgent | None = None,
                 staleness_s: float = 3.0):
        self.worker_id = worker_id
        self.agent = agent or coordination_service()
        self.staleness_s = staleness_s
        self._seq = 0
        # execute() may be called from the Worker dispatch thread AND
        # directly (per-worker resource creation): seq allocation must
        # be atomic or two callers share a task slot
        self._seq_lock = threading.Lock()
        self._last_hb: bytes | None = None
        self._last_change = time.monotonic()

    # -- liveness ---------------------------------------------------------
    def alive(self) -> bool:
        """Heartbeat-derived liveness: the hb VALUE must keep changing.
        Monotonic-local staleness clocking — no cross-host clock trust."""
        hb = self.agent.key_value_try_get(_hb_key(self.worker_id))
        now = time.monotonic()
        if hb is None:
            # never seen: give the worker a startup grace window
            return now - self._last_change < self.staleness_s * 4
        if hb != self._last_hb:
            self._last_hb = hb
            self._last_change = now
            return True
        return now - self._last_change < self.staleness_s

    # -- execution --------------------------------------------------------
    def submit(self, fn: Callable, args: tuple, kwargs: dict) -> int:
        """Publish one closure without waiting; returns its seq (pair
        with :meth:`wait` — lets callers fan tasks out to many lanes
        before blocking on any result)."""
        with self._seq_lock:
            seq = self._seq
            self._seq += 1
        payload = pickle.dumps((fn, args, kwargs))
        self.agent.key_value_set(_task_key(self.worker_id, seq), payload)
        return seq

    def wait(self, seq: int, timeout_s: float | None = None) -> Any:
        """Block for a submitted closure's result; translate worker death
        into WorkerPreemptionError (the retryable class)."""
        from distributed_tensorflow_tpu.coordinator.cluster_coordinator \
            import WorkerPreemptionError
        deadline = (time.monotonic() + timeout_s) if timeout_s else None
        while True:
            res = self.agent.key_value_try_get(
                _result_key(self.worker_id, seq))
            if res is not None:
                break
            if not self.alive():
                raise WorkerPreemptionError(
                    f"worker {self.worker_id} heartbeat stale "
                    f"(>{self.staleness_s}s) while closure {seq} in flight")
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"closure {seq} on worker {self.worker_id} timed out")
            time.sleep(0.02)
        status, data = pickle.loads(res)
        if status == "ok":
            return data
        raise RemoteClosureError(
            f"closure failed on worker {self.worker_id}:\n{data}")

    def execute(self, fn: Callable, args: tuple, kwargs: dict,
                timeout_s: float | None = None) -> Any:
        """submit + wait."""
        return self.wait(self.submit(fn, args, kwargs), timeout_s)


class _ResourceHandle:
    """Worker-side resource reference (≙ per-worker resources: the object
    stays on the worker; the coordinator holds an opaque handle).

    ``builder`` (a picklable zero-arg factory) makes handles SELF-HEALING
    across worker restarts: a restarted worker whose registry lost the
    object rebuilds it on first use instead of failing the closure —
    ≙ the reference re-creating per-worker resources after worker
    recovery (cluster_coordinator.py per-worker dataset re-creation).
    """

    def __init__(self, worker_id: int, handle: int, builder=None):
        self.worker_id = worker_id
        self.handle = handle
        self.builder = builder


def resolve_resources(args, registry: dict):
    """Worker-side: swap _ResourceHandle leaves for the live objects,
    rebuilding missing ones from their builder (worker restarted)."""
    import jax

    def resolve(v):
        if not isinstance(v, _ResourceHandle):
            return v
        if v.handle not in registry:
            if v.builder is None:
                raise KeyError(
                    f"resource handle {v.handle} unknown on this worker "
                    f"(restarted?) and carries no builder")
            registry[v.handle] = v.builder()
        return registry[v.handle]

    return jax.tree_util.tree_map(
        resolve, args, is_leaf=lambda v: isinstance(v, _ResourceHandle))


class RemoteWorkerService:
    """Worker-process service loop (≙ the worker side of the grpc eager
    service): pull task keys in sequence, execute, publish results.

    Run via ``run_worker_loop()`` from a worker task's main; returns when
    the coordinator publishes the shutdown key.
    """

    def __init__(self, worker_id: int | None = None,
                 agent: CoordinationServiceAgent | None = None):
        self.agent = agent or coordination_service()
        self.worker_id = (worker_id if worker_id is not None
                          else self.agent.process_id)
        self.resources: dict[int, Any] = {}
        self._next_handle = 0
        self._stop = threading.Event()
        self._hb_thread: threading.Thread | None = None

    # -- heartbeat --------------------------------------------------------
    def _heartbeat(self):
        n = 0
        while not self._stop.is_set():
            n += 1
            try:
                self.agent.key_value_set(_hb_key(self.worker_id), str(n))
            except Exception:
                return                      # service gone: job is over
            time.sleep(_HEARTBEAT_INTERVAL)

    # -- resource registry (coordinator schedules these as closures) -----
    def create_resource(self, fn, *args, builder=None,
                        **kwargs) -> _ResourceHandle:
        """``builder``: optional picklable zero-arg re-creation factory
        stored on the handle (self-healing across worker restarts)."""
        obj = fn(*args, **kwargs)
        h = self._next_handle
        self._next_handle += 1
        self.resources[h] = obj
        return _ResourceHandle(self.worker_id, h, builder=builder)

    # -- main loop --------------------------------------------------------
    def _initial_seq(self) -> int:
        """Restart support: fast-forward past tasks that already have
        results (a restarted worker must not re-run completed closures)."""
        done = {int(k.rsplit("/", 1)[1]) for k, _ in
                self.agent.key_value_dir_get(
                    f"{_PREFIX}/result/{self.worker_id}/")}
        seq = 0
        while seq in done:
            seq += 1
        return seq

    def run(self, poll_s: float = 0.05):
        self._hb_thread = threading.Thread(target=self._heartbeat,
                                           daemon=True)
        self._hb_thread.start()
        seq = self._initial_seq()
        try:
            while True:
                if self.agent.key_value_try_get(_shutdown_key()) is not None:
                    # ack so the coordinator (which hosts the coordination
                    # service) won't tear it down under our last RPCs
                    self._stop.set()
                    self.agent.key_value_set(
                        f"{_PREFIX}/shutdown_ack/{self.worker_id}", "1")
                    return
                payload = self.agent.key_value_try_get(
                    _task_key(self.worker_id, seq))
                if payload is None:
                    time.sleep(poll_s)
                    continue
                fn, args, kwargs = pickle.loads(payload)
                try:
                    args = resolve_resources(args, self.resources)
                    kwargs = resolve_resources(kwargs, self.resources)
                    # the service instance is discoverable by closures
                    # that create worker-side resources
                    _CURRENT_SERVICE.service = self
                    result = fn(*args, **kwargs)
                    resp = pickle.dumps(("ok", result))
                except BaseException:
                    resp = pickle.dumps(("error", traceback.format_exc()))
                self.agent.key_value_set(
                    _result_key(self.worker_id, seq), resp)
                seq += 1
        finally:
            self._stop.set()


class _CurrentService(threading.local):
    service: "RemoteWorkerService | None" = None


_CURRENT_SERVICE = _CurrentService()


def current_worker_service() -> RemoteWorkerService | None:
    """Inside a remotely dispatched closure: the hosting service (for
    creating worker-side resources)."""
    return _CURRENT_SERVICE.service


def run_worker_loop(worker_id: int | None = None):
    """Entry point for a worker task: serve closures until shutdown.

    Usage (worker main, after ``bootstrap.initialize()``)::

        if runtime.process_id != 0:
            remote_dispatch.run_worker_loop()
            return
    """
    RemoteWorkerService(worker_id).run()


def shutdown_workers(agent: CoordinationServiceAgent | None = None,
                     worker_ids: "list[int] | None" = None,
                     timeout_s: float = 15.0):
    """Coordinator-side: tell every worker service loop to return, then
    wait for acks — the coordinator hosts the coordination service, so it
    must not exit while workers still have RPCs in flight."""
    agent = agent or coordination_service()
    agent.key_value_set(_shutdown_key(), "1")
    deadline = time.monotonic() + timeout_s
    pending = set(worker_ids or ())
    while pending and time.monotonic() < deadline:
        for wid in list(pending):
            if agent.key_value_try_get(
                    f"{_PREFIX}/shutdown_ack/{wid}") is not None:
                pending.discard(wid)
        if pending:
            time.sleep(0.05)
    # Retire the whole namespace (TSL key_value_delete is recursive for
    # directories): a later coordinator/worker generation in the same job
    # must not read this generation's shutdown key, stale results
    # (RemoteLane seqs restart at 0!), or heartbeats.
    try:
        agent.key_value_delete(_PREFIX)
    except Exception:
        pass
