"""Remote closure dispatch: coordinator -> worker processes.

TPU-native counterpart of the reference's remote execution path in
tensorflow/python/distribute/coordinator/cluster_coordinator.py:1027
(``Worker`` — one grpc-backed remote executor per worker process) and
:879 (``WorkerPreemptionHandler.wait_on_failure`` — grpc UnavailableError
from a dead worker triggers transparent re-dispatch).

The reference's transport is the grpc eager service; the TPU-native
control plane is the TSL coordination service that every process is
already connected to (cluster/coordination.py), so closures ride its KV
store:

    coordinator                           worker process w
    -----------                           ----------------
    g<G>/task/<w>/<seq> <- pickle(...)    blocking get g<G>/task/<w>/<seq>
    blocking get g<G>/result/<w>/<seq>    run fn
      | heartbeat stale?                  set g<G>/result/<w>/<seq>
      v                                   g<G>/done/<w> = seq+1 (watermark)
    WorkerPreemptionError -> re-queue     seq += 1

Lifecycle rules (a long async-PS job schedules 10^5-10^6 closures, so the
KV store must stay bounded — ≙ the reference's per-closure grpc calls
leaving nothing behind):

- Every key lives under a per-coordinator-incarnation GENERATION
  namespace ``g<G>`` (G from an atomic counter). A crash-restarted
  coordinator gets a fresh G and can never read a prior incarnation's
  results; workers follow the published ``current_gen``.
- The coordinator DELETES task+result keys as soon as a result is
  consumed; the worker's restart fast-forward reads the ``done/<w>``
  watermark instead of scanning result keys.
- Waits are BLOCKING coordination-service gets (no 20 ms polling): one
  RPC per staleness window instead of 50/s per lane.

Death detection is organic: each worker service bumps a heartbeat key a
few times a second; a coordinator lane that stops seeing bumps while
waiting raises ``WorkerPreemptionError`` — the producer the retry
machinery in cluster_coordinator.py needs. This is a CONTROL plane: data
(model state) moves inside SPMD programs over ICI/DCN, not through the
KV store (``MAX_PAYLOAD_BYTES`` enforces it).
"""

from __future__ import annotations

import pickle
import threading
import time
import traceback
from typing import Any, Callable

from distributed_tensorflow_tpu.cluster.coordination import (
    CoordinationError,
    CoordinationServiceAgent,
    coordination_service,
)
from distributed_tensorflow_tpu.resilience import faults
from distributed_tensorflow_tpu.resilience.retry import Backoff, RetryPolicy
from distributed_tensorflow_tpu.telemetry import events as telemetry_events
from distributed_tensorflow_tpu.telemetry import registry as telemetry_registry

_ROOT = "dtx_coord"
_HEARTBEAT_INTERVAL = 0.2

#: Pacing for the fast-fail path inside :meth:`RemoteLane.wait` — a
#: coordination-service error (not a timeout) backs off along this
#: schedule instead of hot-spinning until the staleness window closes.
#: Shared policy object (resilience/retry.py) rather than ad-hoc sleeps.
_WAIT_BACKOFF_POLICY = RetryPolicy(initial_backoff_s=0.05,
                                   backoff_multiplier=2.0,
                                   max_backoff_s=0.1)

#: Closure payloads ride the coordination service's KV store, which is a
#: control plane. Anything bigger than this belongs in the SPMD data
#: plane (device arrays / checkpoints), not in a pickled closure.
MAX_PAYLOAD_BYTES = 4 << 20


class RemoteClosureError(RuntimeError):
    """The closure raised on the worker; carries the remote traceback."""


def _hb_key(worker_id: int) -> str:
    return f"{_ROOT}/hb/{worker_id}"


def _gen_dir(gen: int) -> str:
    return f"{_ROOT}/g{gen}"


def _task_key(gen: int, worker_id: int, seq: int) -> str:
    return f"{_gen_dir(gen)}/task/{worker_id}/{seq}"


def _result_key(gen: int, worker_id: int, seq: int) -> str:
    return f"{_gen_dir(gen)}/result/{worker_id}/{seq}"


def closure_span_id(gen: int, worker_id: int, seq: int) -> str:
    """Stable causality id one closure carries across processes: the
    coordinator's ``dispatch.send``/``dispatch.result`` events and the
    worker's ``worker.execute`` span all stamp it, so the merged trace
    (telemetry/trace.py) links them into one flow chain. (gen, worker,
    seq) already uniquely names a closure on the KV control plane — the
    span id is just its printable form."""
    return f"dispatch/g{gen}/w{worker_id}/c{seq}"


def _done_key(gen: int, worker_id: int) -> str:
    """Watermark: next seq this worker should run (restart fast-forward)."""
    return f"{_gen_dir(gen)}/done/{worker_id}"


def _shutdown_key(gen: int) -> str:
    return f"{_gen_dir(gen)}/shutdown"


# ---------------------------------------------------------------------------
# Generations: one per coordinator incarnation.
# ---------------------------------------------------------------------------

_GEN_LOCK = threading.Lock()
_GENERATION: int | None = None


def _coordinator_generation(agent: CoordinationServiceAgent) -> int:
    """This coordinator process's generation — allocated once, published
    as ``current_gen`` for workers to follow. A restarted coordinator
    allocates a fresh one, so stale task/result keys from a crashed
    incarnation are unreachable (and its immediate predecessor's
    namespace is garbage-collected here)."""
    global _GENERATION
    with _GEN_LOCK:
        if _GENERATION is None:
            gen = agent.key_value_increment(f"{_ROOT}/generation")
            if gen > 1:        # GC a crashed predecessor's namespace
                try:
                    agent.key_value_delete(_gen_dir(gen - 1))
                except Exception:
                    pass
            agent.key_value_set(f"{_ROOT}/current_gen", str(gen))
            _GENERATION = gen
        return _GENERATION


def _reset_generation_for_tests():
    global _GENERATION
    with _GEN_LOCK:
        _GENERATION = None


class RemoteLane:
    """Coordinator-side handle to one worker process (≙ the grpc channel
    + remote executor inside cluster_coordinator.Worker :1027)."""

    def __init__(self, worker_id: int,
                 agent: CoordinationServiceAgent | None = None,
                 staleness_s: float = 3.0):
        self.worker_id = worker_id
        self.agent = agent or coordination_service()
        self.staleness_s = staleness_s
        self.generation = _coordinator_generation(self.agent)
        self._seq = 0
        # execute() may be called from the Worker dispatch thread AND
        # directly (per-worker resource creation): seq allocation must
        # be atomic or two callers share a task slot
        self._seq_lock = threading.Lock()
        # Consumed-seq bookkeeping for the restart watermark: this lane
        # is the SOLE writer of done/<w> (a single writer cannot race
        # itself), advancing it to the contiguous consumed prefix.
        self._consumed: set[int] = set()
        self._watermark = 0
        self._last_hb: bytes | None = None
        self._last_change = time.monotonic()

    # -- liveness ---------------------------------------------------------
    def alive(self) -> bool:
        """Heartbeat-derived liveness: the hb VALUE must keep changing.
        Monotonic-local staleness clocking — no cross-host clock trust."""
        hb = self.agent.key_value_try_get(_hb_key(self.worker_id))
        now = time.monotonic()
        if hb is None:
            # never seen: give the worker a startup grace window
            return now - self._last_change < self.staleness_s * 4
        if hb != self._last_hb:
            self._last_hb = hb
            self._last_change = now
            return True
        return now - self._last_change < self.staleness_s

    # -- execution --------------------------------------------------------
    def submit(self, fn: Callable, args: tuple, kwargs: dict) -> int:
        """Publish one closure without waiting; returns its seq (pair
        with :meth:`wait` — lets callers fan tasks out to many lanes
        before blocking on any result)."""
        payload = pickle.dumps((fn, args, kwargs))
        if len(payload) > MAX_PAYLOAD_BYTES:
            raise ValueError(
                f"closure payload is {len(payload)} bytes "
                f"(> {MAX_PAYLOAD_BYTES}): the KV control plane is not a "
                f"data path — move bulk data via SPMD programs, "
                f"checkpoints, or per-worker datasets")
        with self._seq_lock:
            seq = self._seq
            self._seq += 1
        if telemetry_events.enabled():
            # span_id threads the closure through the merged timeline:
            # dispatch.send (coordinator) -> worker.execute (worker) ->
            # dispatch.result (coordinator) render as one flow chain in
            # the assembled trace (telemetry/trace.py).
            telemetry_events.event(
                "dispatch.send", worker=self.worker_id, closure=seq,
                span_id=closure_span_id(self.generation, self.worker_id,
                                        seq))
        self.agent.key_value_set(
            _task_key(self.generation, self.worker_id, seq), payload)
        return seq

    def wait(self, seq: int, timeout_s: float | None = None) -> Any:
        """Block for a submitted closure's result; translate worker death
        into WorkerPreemptionError (the retryable class). Consumed task +
        result keys are deleted — the KV store stays bounded regardless
        of how many closures the job schedules."""
        from distributed_tensorflow_tpu.coordinator.cluster_coordinator \
            import WorkerPreemptionError
        # Stall attribution: while this lane blocks (including inside an
        # injected dispatch.wait chaos delay), the telemetry stall
        # detector can see WHICH worker the coordinator is waiting on
        # (telemetry/stall.suspect_worker reads these gauges).
        wait_gauge = telemetry_registry.gauge(
            f"coordinator/dispatch/waiting/{self.worker_id}")
        wait_gauge.set(time.monotonic())
        try:
            faults.fire("dispatch.wait", tag=self.worker_id,
                        exc=WorkerPreemptionError,
                        msg=f"injected preemption: worker "
                            f"{self.worker_id}, closure {seq}")
            deadline = (time.monotonic() + timeout_s) if timeout_s else None
            rkey = _result_key(self.generation, self.worker_id, seq)
            backoff = Backoff(_WAIT_BACKOFF_POLICY)
            return self._wait_inner(seq, rkey, deadline, backoff)
        finally:
            wait_gauge.set(None)

    def _wait_inner(self, seq: int, rkey: str, deadline, backoff):
        from distributed_tensorflow_tpu.coordinator.cluster_coordinator \
            import WorkerPreemptionError
        while True:
            # Blocking get in staleness-sized slices: wakes immediately
            # when the worker publishes, touches the service once per
            # slice otherwise (vs the previous 50 polls/s).
            slice_s = self.staleness_s
            if deadline is not None:
                slice_s = min(slice_s, max(deadline - time.monotonic(),
                                           0.01))
            t0 = time.monotonic()
            try:
                res = self.agent.key_value_get(rkey, timeout_s=slice_s)
                break
            except CoordinationError:
                # Not published yet — but if the get failed FAST (service
                # error, not a timeout), back off instead of hot-spinning
                # until the heartbeat staleness window closes.
                waited = time.monotonic() - t0
                if waited < slice_s:
                    backoff.sleep(max_s=slice_s - waited)
                else:
                    backoff.reset()      # full slice elapsed: not an error
            if not self.alive():
                telemetry_events.event("dispatch.preempted",
                                       worker=self.worker_id, closure=seq,
                                       staleness_s=self.staleness_s)
                raise WorkerPreemptionError(
                    f"worker {self.worker_id} heartbeat stale "
                    f"(>{self.staleness_s}s) while closure {seq} in flight")
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"closure {seq} on worker {self.worker_id} timed out")
        # Consume: advance the restart watermark BEFORE deleting the
        # task/result keys, so a restarted worker can never block on a
        # task key this consume already deleted. The lane is the single
        # writer of done/<w>, advancing it to the CONTIGUOUS consumed
        # prefix under the lane lock (two lane threads consuming
        # different seqs cannot regress it; a completed-but-unconsumed
        # seq above the watermark is simply re-run after a restart —
        # its task key still exists and re-publishing the result is
        # idempotent at-least-once, the reference's retry semantics).
        with self._seq_lock:
            self._consumed.add(seq)
            advanced = False
            while self._watermark in self._consumed:
                self._consumed.discard(self._watermark)
                self._watermark += 1
                advanced = True
            if advanced:
                try:
                    self.agent.key_value_set(
                        _done_key(self.generation, self.worker_id),
                        str(self._watermark))
                except Exception:
                    pass
        for k in (rkey, _task_key(self.generation, self.worker_id, seq)):
            try:
                self.agent.key_value_delete(k)
            except Exception:
                pass
        status, data = pickle.loads(res)
        if status == "ok":
            if telemetry_events.enabled():
                telemetry_events.event(
                    "dispatch.result", worker=self.worker_id, closure=seq,
                    span_id=closure_span_id(self.generation,
                                            self.worker_id, seq))
            return data
        telemetry_events.event("dispatch.closure_error",
                               worker=self.worker_id, closure=seq,
                               span_id=closure_span_id(
                                   self.generation, self.worker_id, seq))
        raise RemoteClosureError(
            f"closure failed on worker {self.worker_id}:\n{data}")

    def execute(self, fn: Callable, args: tuple, kwargs: dict,
                timeout_s: float | None = None) -> Any:
        """submit + wait."""
        return self.wait(self.submit(fn, args, kwargs), timeout_s)


class _ResourceHandle:
    """Worker-side resource reference (≙ per-worker resources: the object
    stays on the worker; the coordinator holds an opaque handle).

    ``builder`` (a picklable zero-arg factory) makes handles SELF-HEALING
    across worker restarts: a restarted worker whose registry lost the
    object rebuilds it on first use instead of failing the closure —
    ≙ the reference re-creating per-worker resources after worker
    recovery (cluster_coordinator.py per-worker dataset re-creation).
    Handle ids embed the worker INCARNATION (an atomic counter bumped at
    service start), so a stale handle can never alias a fresh resource
    on a restarted worker — it misses the registry and rebuilds.
    """

    def __init__(self, worker_id: int, handle: str, builder=None):
        self.worker_id = worker_id
        self.handle = handle
        self.builder = builder


def resolve_resources(args, registry: dict):
    """Worker-side: swap _ResourceHandle leaves for the live objects,
    rebuilding missing ones from their builder (worker restarted)."""
    import jax

    def resolve(v):
        if not isinstance(v, _ResourceHandle):
            return v
        if v.handle not in registry:
            if v.builder is None:
                raise KeyError(
                    f"resource handle {v.handle} unknown on this worker "
                    f"(restarted?) and carries no builder")
            registry[v.handle] = v.builder()
        return registry[v.handle]

    return jax.tree_util.tree_map(
        resolve, args, is_leaf=lambda v: isinstance(v, _ResourceHandle))


class RemoteWorkerService:
    """Worker-process service loop (≙ the worker side of the grpc eager
    service): pull task keys in sequence, execute, publish results.

    Run via ``run_worker_loop()`` from a worker task's main; returns when
    the coordinator publishes the shutdown key. Follows the published
    ``current_gen``: if a new coordinator incarnation appears mid-loop,
    the service switches namespaces and resumes from the new generation's
    watermark.
    """

    def __init__(self, worker_id: int | None = None,
                 agent: CoordinationServiceAgent | None = None):
        self.agent = agent or coordination_service()
        self.worker_id = (worker_id if worker_id is not None
                          else self.agent.process_id)
        self.resources: dict[str, Any] = {}
        self._incarnation = self.agent.key_value_increment(
            f"{_ROOT}/incarnation/{self.worker_id}")
        self._next_handle = 0
        self._stop = threading.Event()
        self._hb_thread: threading.Thread | None = None

    # -- heartbeat --------------------------------------------------------
    def _heartbeat(self):
        n = 0
        while not self._stop.is_set():
            n += 1
            try:
                self.agent.key_value_set(_hb_key(self.worker_id),
                                         f"{self._incarnation}:{n}")
            except Exception:
                return                      # service gone: job is over
            time.sleep(_HEARTBEAT_INTERVAL)

    # -- resource registry (coordinator schedules these as closures) -----
    def create_resource(self, fn, *args, builder=None,
                        **kwargs) -> _ResourceHandle:
        """``builder``: optional picklable zero-arg re-creation factory
        stored on the handle (self-healing across worker restarts)."""
        obj = fn(*args, **kwargs)
        h = f"{self._incarnation}:{self._next_handle}"
        self._next_handle += 1
        self.resources[h] = obj
        return _ResourceHandle(self.worker_id, h, builder=builder)

    # -- main loop --------------------------------------------------------
    def _current_gen(self) -> int | None:
        raw = self.agent.key_value_try_get(f"{_ROOT}/current_gen")
        return int(raw) if raw is not None else None

    def _initial_seq(self, gen: int) -> int:
        """Restart support: resume from the completed-seq watermark (a
        restarted worker must not re-run completed closures)."""
        raw = self.agent.key_value_try_get(_done_key(gen, self.worker_id))
        return int(raw) if raw is not None else 0

    def run(self, poll_s: float = 0.5):
        """Serve closures until the coordinator's shutdown key appears.

        ``poll_s`` is the blocking-get slice for the task key — purely a
        shutdown/generation-switch responsiveness bound, not a poll rate
        (the get wakes immediately when a task is published).
        """
        self._hb_thread = threading.Thread(target=self._heartbeat,
                                           daemon=True)
        self._hb_thread.start()
        gen: int | None = None
        seq = 0
        try:
            while True:
                cur = self._current_gen()
                if cur is None:          # no coordinator incarnation yet
                    time.sleep(min(poll_s, 0.05))
                    continue
                if cur != gen:           # adopt the (new) coordinator
                    gen, seq = cur, self._initial_seq(cur)
                if self.agent.key_value_try_get(
                        _shutdown_key(gen)) is not None:
                    # ack so the coordinator (which hosts the coordination
                    # service) won't tear it down under our last RPCs
                    self._stop.set()
                    self.agent.key_value_set(
                        f"{_gen_dir(gen)}/shutdown_ack/{self.worker_id}",
                        "1")
                    return
                try:
                    payload = self.agent.key_value_get(
                        _task_key(gen, self.worker_id, seq),
                        timeout_s=poll_s)
                except CoordinationError:
                    continue             # no task yet: re-check shutdown
                fn, args, kwargs = pickle.loads(payload)
                try:
                    with telemetry_registry.timer(
                            "worker/closure_execution").time(), \
                        telemetry_events.span(
                            "worker.execute", worker=self.worker_id,
                            closure=seq,
                            span_id=closure_span_id(gen, self.worker_id,
                                                    seq)):
                        args = resolve_resources(args, self.resources)
                        kwargs = resolve_resources(kwargs, self.resources)
                        # the service instance is discoverable by closures
                        # that create worker-side resources
                        _CURRENT_SERVICE.service = self
                        result = fn(*args, **kwargs)
                    resp = pickle.dumps(("ok", result))
                    telemetry_registry.counter(
                        "worker/closures_executed").increment()
                except BaseException:
                    resp = pickle.dumps(("error", traceback.format_exc()))
                    telemetry_registry.counter(
                        "worker/closures_failed").increment()
                    telemetry_events.event("worker.closure_error",
                                           worker=self.worker_id, seq=seq)
                # The coordinator (sole watermark writer) advances
                # done/<w> as it consumes; the worker only publishes the
                # result and moves on.
                self.agent.key_value_set(
                    _result_key(gen, self.worker_id, seq), resp)
                seq += 1
        finally:
            self._stop.set()


class _CurrentService(threading.local):
    service: "RemoteWorkerService | None" = None


_CURRENT_SERVICE = _CurrentService()


def current_worker_service() -> RemoteWorkerService | None:
    """Inside a remotely dispatched closure: the hosting service (for
    creating worker-side resources)."""
    return _CURRENT_SERVICE.service


def run_worker_loop(worker_id: int | None = None):
    """Entry point for a worker task: serve closures until shutdown.

    Usage (worker main, after ``bootstrap.initialize()``)::

        if runtime.process_id != 0:
            remote_dispatch.run_worker_loop()
            return
    """
    RemoteWorkerService(worker_id).run()


def shutdown_workers(agent: CoordinationServiceAgent | None = None,
                     worker_ids: "list[int] | None" = None,
                     timeout_s: float = 15.0):
    """Coordinator-side: tell every worker service loop to return, then
    wait for acks — the coordinator hosts the coordination service, so it
    must not exit while workers still have RPCs in flight."""
    agent = agent or coordination_service()
    gen = _coordinator_generation(agent)
    agent.key_value_set(_shutdown_key(gen), "1")
    deadline = time.monotonic() + timeout_s
    pending = set(worker_ids or ())
    while pending and time.monotonic() < deadline:
        for wid in list(pending):
            if agent.key_value_try_get(
                    f"{_gen_dir(gen)}/shutdown_ack/{wid}") is not None:
                pending.discard(wid)
        if pending:
            time.sleep(0.05)
    # Retire this generation's namespace + the heartbeat keys (TSL
    # key_value_delete is recursive for directories). The generation
    # counter itself survives: a later coordinator in the same job gets a
    # strictly newer incarnation.
    for key in (_gen_dir(gen), f"{_ROOT}/hb"):
        try:
            agent.key_value_delete(key)
        except Exception:
            pass
