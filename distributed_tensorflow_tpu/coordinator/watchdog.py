"""Coordinator watchdog: dump all-thread stacks on inactivity.

≙ tensorflow/python/distribute/coordinator/watchdog.py:25 ``WatchDog``
(SURVEY.md §2.5, §5.2): if the coordinator makes no progress for
``timeout`` seconds, dump every thread's stack to aid hang debugging.
"""

from __future__ import annotations

import faulthandler
import sys
import threading
import time
from typing import Callable


class WatchDog:
    def __init__(self, timeout: float = 300.0,
                 on_triggered: Callable[[], None] | None = None,
                 output=sys.stderr):
        self._timeout = timeout
        self._on_triggered = on_triggered
        self._output = output
        self._last_activity = time.time()
        self._stop = threading.Event()
        self._triggered_count = 0
        self._thread = threading.Thread(target=self._watch, daemon=True,
                                        name="dtx-watchdog")
        self._thread.start()

    def report_activity(self):
        self._last_activity = time.time()

    def set_timeout(self, timeout: float):
        """Re-arm with a new inactivity budget (the telemetry stall
        detector tracks a multiple of the trailing median step time).
        Takes effect within the current 1s wait slice."""
        self._timeout = timeout

    @property
    def triggered_count(self) -> int:
        return self._triggered_count

    def _watch(self):
        while not self._stop.wait(min(self._timeout / 10, 1.0)):
            if time.time() - self._last_activity > self._timeout:
                self._triggered_count += 1
                self._last_activity = time.time()
                try:
                    print(f"[dtx WatchDog] no coordinator activity for "
                          f">{self._timeout}s; dumping stacks",
                          file=self._output, flush=True)
                    faulthandler.dump_traceback(file=self._output)
                except Exception:
                    pass
                if self._on_triggered is not None:
                    # a user callback that raises must not kill the
                    # watch loop — the watchdog outlives its hooks
                    try:
                        self._on_triggered()
                    except Exception:
                        try:
                            print("[dtx WatchDog] on_triggered raised "
                                  "(ignored)", file=self._output,
                                  flush=True)
                        except Exception:
                            pass

    def stop(self, timeout: float | None = 5.0):
        """Stop AND join the watch thread, so no trigger can fire after
        stop() returns (a dangling watch thread dumping stacks into a
        closed test capture was the previous failure mode)."""
        self._stop.set()
        if self._thread is not threading.current_thread():
            self._thread.join(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
