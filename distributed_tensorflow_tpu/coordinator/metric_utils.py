"""Coordinator metrics: counters and timers.

≙ tensorflow/python/distribute/coordinator/metric_utils.py (SURVEY.md §2.5,
:89 ``monitored_timer``) and the tf.monitoring gauges in distribute_lib
(SURVEY §5.5). Since the telemetry subsystem landed these are thin
back-compat shims over :mod:`distributed_tensorflow_tpu.telemetry`
instruments: the classes keep their historical constructor/property
surface (``Counter(name).value``, ``Timer(name).time()``,
``total_seconds``/``average_seconds``) and additionally self-register in
the process-wide MetricsRegistry under ``coordinator/<name>`` — so
coordinator activity shows up in registry snapshots, fleet rollups, and
``tools/obs_report.py`` without any caller changing.

Instances own their storage (one closure queue per Cluster keeps its own
counts); registration is latest-wins, so the registry always reads the
live instance.
"""

from __future__ import annotations

from distributed_tensorflow_tpu.telemetry import registry as _telemetry

_NAMESPACE = "coordinator"


def _register(instrument, name: str):
    _telemetry.get_registry().register(instrument,
                                       f"{_NAMESPACE}/{name}")
    return instrument


class Counter(_telemetry.Counter):
    def __init__(self, name: str, description: str = ""):
        super().__init__(name, description)
        _register(self, name)


class Gauge(_telemetry.Gauge):
    """≙ tf.monitoring StringGauge/IntGauge (distribution_strategy_gauge)."""

    def __init__(self, name: str, description: str = ""):
        super().__init__(name, description)
        _register(self, name)


class Timer(_telemetry.Timer):
    """Accumulating timer (≙ monitored_timer, metric_utils.py:89)."""

    def __init__(self, name: str, description: str = ""):
        super().__init__(name, description)
        _register(self, name)


class CoordinatorMetrics:
    """The queued/inflight/execution instrument set (≙ metric_utils.py)."""

    def __init__(self):
        self.closure_execution = Timer("closure_execution")
        self.remote_value_fetch = Timer("remote_value_fetch")
        self.queued = Gauge("queued_closures")
        self.inflight = Gauge("inflight_closures")

# global gauges ≙ distribution_strategy_gauge (distribute_lib.py top)
strategy_gauge = Gauge("distribution_strategy")
replica_gauge = Gauge("num_replicas")
