"""Coordinator metrics: counters and timers.

≙ tensorflow/python/distribute/coordinator/metric_utils.py (SURVEY.md §2.5,
:89 ``monitored_timer``) and the tf.monitoring gauges in distribute_lib
(SURVEY §5.5). Plain-Python instruments: thread-safe, inspectable, no
backend dependency.
"""

from __future__ import annotations

import contextlib
import threading
import time


class Counter:
    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def increment(self, n: int = 1):
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """≙ tf.monitoring StringGauge/IntGauge (distribution_strategy_gauge)."""

    def __init__(self, name: str):
        self.name = name
        self._value = None
        self._lock = threading.Lock()

    def set(self, value):
        with self._lock:
            self._value = value

    @property
    def value(self):
        with self._lock:
            return self._value


class Timer:
    """Accumulating timer (≙ monitored_timer, metric_utils.py:89)."""

    def __init__(self, name: str):
        self.name = name
        self._total = 0.0
        self._count = 0
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def time(self):
        start = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - start
            with self._lock:
                self._total += dt
                self._count += 1

    @property
    def total_seconds(self) -> float:
        with self._lock:
            return self._total

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def average_seconds(self) -> float:
        with self._lock:
            return self._total / self._count if self._count else 0.0


class CoordinatorMetrics:
    """The queued/inflight/execution instrument set (≙ metric_utils.py)."""

    def __init__(self):
        self.closure_execution = Timer("closure_execution")
        self.remote_value_fetch = Timer("remote_value_fetch")
        self.queued = Gauge("queued_closures")
        self.inflight = Gauge("inflight_closures")

# global gauges ≙ distribution_strategy_gauge (distribute_lib.py top)
strategy_gauge = Gauge("distribution_strategy")
replica_gauge = Gauge("num_replicas")
