"""Legacy distribute coordinator — the Estimator-era orchestration entry.

≙ tensorflow/python/distribute/distribute_coordinator.py (872 LoC:
``run_distribute_coordinator`` :627, ``DistributeCoordinatorMode``,
``_WorkerContext`` — SURVEY.md §2.1 last row). The reference spawned
std-server threads and ran ``worker_fn`` between-graph per task; the
TPU-native runtime has no graph servers — INDEPENDENT_WORKER maps onto
bootstrap.initialize (every process runs the same SPMD program) and
STANDALONE_CLIENT onto a local run. Retained as the compatibility entry
point for ported ``train_and_evaluate`` scripts; new code should use
``Strategy`` + ``Model.fit`` directly.
"""

from __future__ import annotations

import enum
from typing import Callable

from distributed_tensorflow_tpu.cluster import bootstrap
from distributed_tensorflow_tpu.cluster.resolver import (
    ClusterSpec,
    SimpleClusterResolver,
    TFConfigClusterResolver,
)


class CoordinatorMode(enum.Enum):
    """≙ DistributeCoordinatorMode."""
    STANDALONE_CLIENT = "standalone_client"
    INDEPENDENT_WORKER = "independent_worker"


class WorkerContext:
    """What ``worker_fn`` receives (≙ _WorkerContext): cluster facts plus
    the strategy, already entered."""

    def __init__(self, strategy, cluster_spec: ClusterSpec,
                 task_type: str | None, task_id: int | None):
        self.strategy = strategy
        self.cluster_spec = cluster_spec
        self.task_type = task_type
        self.task_id = task_id

    @property
    def is_chief(self) -> bool:
        from distributed_tensorflow_tpu.cluster.resolver import is_chief
        if not self.cluster_spec or self.task_type is None:
            return True
        return is_chief(self.cluster_spec, self.task_type,
                        self.task_id or 0)

    @property
    def distributed_mode(self) -> bool:
        return bool(self.cluster_spec)


def run_distribute_coordinator(
        worker_fn: Callable, strategy,
        mode: CoordinatorMode = CoordinatorMode.INDEPENDENT_WORKER,
        cluster_spec: ClusterSpec | dict | None = None,
        task_type: str | None = None, task_id: int | None = None):
    """≙ run_distribute_coordinator (:627): resolve the cluster, connect
    the runtime, and run ``worker_fn(context)`` under the strategy scope.

    INDEPENDENT_WORKER: every task calls this with its own TF_CONFIG
    (or explicit spec) — processes join via the coordination service and
    execute the one SPMD program together. STANDALONE_CLIENT: run
    locally against whatever devices are visible.
    """
    if isinstance(cluster_spec, dict):
        cluster_spec = ClusterSpec(cluster_spec)
    if cluster_spec is None:
        resolver = TFConfigClusterResolver()
        cluster_spec = resolver.cluster_spec()
        task_type = task_type or resolver.task_type
        task_id = task_id if task_id is not None else resolver.task_id
    else:
        resolver = SimpleClusterResolver(cluster_spec,
                                         task_type=task_type or "",
                                         task_id=task_id or 0)

    from distributed_tensorflow_tpu.cluster.resolver import EVALUATOR
    if (mode is CoordinatorMode.INDEPENDENT_WORKER and cluster_spec
            and task_type != EVALUATOR):
        # The evaluator task is its own single-task world (≙ the
        # reference's "evaluator" special case :627): it must never join
        # the SPMD rendezvous or trainers' collectives would wait on it.
        bootstrap.initialize(resolver=resolver)

    ctx = WorkerContext(strategy, cluster_spec, task_type, task_id)
    if strategy is None:
        # strategy-less orchestration (the worker_fn builds its own
        # sharded programs, e.g. train_and_evaluate roles)
        return worker_fn(ctx)
    with strategy.scope():
        return worker_fn(ctx)
