"""Chief-side async dispatch for parameter-server training.

TPU-native counterpart of tensorflow/python/distribute/coordinator/
(SURVEY.md §2.5).
"""

from distributed_tensorflow_tpu.coordinator.cluster_coordinator import (
    ClusterCoordinator,
    Closure,
    PerWorkerValues,
    PSUnavailableError,
    RemoteValue,
    WorkerPreemptionError,
)
from distributed_tensorflow_tpu.coordinator.watchdog import WatchDog
