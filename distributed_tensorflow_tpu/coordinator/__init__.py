"""Chief-side async dispatch for parameter-server training.

TPU-native counterpart of tensorflow/python/distribute/coordinator/
(SURVEY.md §2.5).
"""

from distributed_tensorflow_tpu.coordinator.cluster_coordinator import (
    ClusterCoordinator,
    Closure,
    PerWorkerValues,
    PSUnavailableError,
    RemoteValue,
    WorkerPreemptionError,
)
from distributed_tensorflow_tpu.coordinator.watchdog import WatchDog
from distributed_tensorflow_tpu.coordinator import remote_dispatch
from distributed_tensorflow_tpu.coordinator.distribute_coordinator import (
    CoordinatorMode,
    WorkerContext,
    run_distribute_coordinator,
)
from distributed_tensorflow_tpu.coordinator.evaluator import (
    SidecarEvaluator,
    train_and_evaluate,
)
