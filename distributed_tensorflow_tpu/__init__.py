"""distributed_tensorflow_tpu — a TPU-native distributed training framework.

A ground-up re-design of the capabilities of the reference
``BaiYuYuan/distributed-tensorflow`` (a TensorFlow fork whose core surface is
the NCCL-backed ``tf.distribute`` stack — see SURVEY.md) built idiomatically
on JAX/XLA for TPU:

- NCCL / ring allreduce            -> XLA collectives over ICI (psum et al.)
- grpc worker data plane           -> single-program SPMD execution (pjit)
- TF_CONFIG cluster resolution     -> kept, plus TPU-VM metadata discovery
- DistributedVariable              -> sharded ``jax.Array`` with NamedSharding
- MirroredStrategy / MWMS / PS     -> Strategy API over a ``jax.sharding.Mesh``
- coordination service             -> ``jax.distributed`` (TSL coord service)

Conventional import:

    import distributed_tensorflow_tpu as dtx
"""

from distributed_tensorflow_tpu.utils import jax_compat as _jax_compat

_jax_compat.install()   # backfill jax.shard_map & friends on old jax

from distributed_tensorflow_tpu.cluster.topology import (
    Topology,
    DeviceAssignment,
    make_mesh,
)
from distributed_tensorflow_tpu.cluster.resolver import (
    ClusterSpec,
    ClusterResolver,
    SimpleClusterResolver,
    TFConfigClusterResolver,
    TPUClusterResolver,
)
from distributed_tensorflow_tpu.cluster import bootstrap
from distributed_tensorflow_tpu.cluster.bootstrap import initialize

from distributed_tensorflow_tpu.parallel.collectives import (
    CollectiveType,
    ReduceOp,
    CommunicationImplementation,
    CommunicationOptions,
)
from distributed_tensorflow_tpu.parallel import collectives
from distributed_tensorflow_tpu.parallel.values import (
    DistributedValues,
    PerReplica,
    Mirrored,
    DistributedVariable,
    MirroredVariable,
    SyncOnReadVariable,
    VariableSynchronization,
    VariableAggregation,
)
from distributed_tensorflow_tpu.parallel.sharded_variable import (
    Partitioner,
    FixedShardsPartitioner,
    MinSizePartitioner,
    MaxSizePartitioner,
    ShardedVariable,
)
from distributed_tensorflow_tpu.parallel.cross_device_ops import (
    CrossDeviceOps,
    ReductionToOneDevice,
    IciAllReduce,
    HierarchicalAllReduce,
    select_cross_device_ops,
)
from distributed_tensorflow_tpu.parallel.strategy import (
    Strategy,
    ReplicaContext,
    get_replica_context,
    get_strategy,
    has_strategy,
    in_cross_replica_context,
)
from distributed_tensorflow_tpu.parallel.one_device import OneDeviceStrategy
from distributed_tensorflow_tpu.parallel.mirrored import MirroredStrategy
from distributed_tensorflow_tpu.parallel.multi_worker import (
    MultiWorkerMirroredStrategy,
    CollectiveAllReduceStrategy,
)
from distributed_tensorflow_tpu.parallel.tpu_strategy import TPUStrategy
from distributed_tensorflow_tpu.parallel.parameter_server import (
    ParameterServerStrategy,
    ParameterServerStrategyV1,
    ParameterServerStrategyV2,
)
from distributed_tensorflow_tpu.parallel.central_storage import (
    CentralStorageStrategy,
)
from distributed_tensorflow_tpu.parallel.ps_values import (
    AggregatingVariable,
    CachingVariable,
)
from distributed_tensorflow_tpu.cluster.platform_resolvers import (
    GCEClusterResolver,
    KubernetesClusterResolver,
    SageMakerClusterResolver,
    SlurmClusterResolver,
)

from distributed_tensorflow_tpu.input.dataset import (
    AutoShardPolicy,
    InputOptions,
    Dataset,
    DistributedDataset,
)

from distributed_tensorflow_tpu import models
from distributed_tensorflow_tpu import ops
from distributed_tensorflow_tpu import training
from distributed_tensorflow_tpu import keras
from distributed_tensorflow_tpu import embedding
from distributed_tensorflow_tpu.cluster.coordination import (
    coordination_service,
)
from distributed_tensorflow_tpu import resilience
from distributed_tensorflow_tpu.resilience import RetryPolicy
from distributed_tensorflow_tpu import serving
from distributed_tensorflow_tpu.utils import bfloat16
from distributed_tensorflow_tpu.utils import summary
from distributed_tensorflow_tpu.utils import tensor_tracer

__version__ = "0.1.0"
