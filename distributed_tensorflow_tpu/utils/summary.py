"""Minimal TensorBoard summary writer — no TensorFlow dependency.

≙ the reference's metrics/observability path (SURVEY.md §5.5:
tf.summary scalar writing + monitoring gauges). Event files are written
in the exact format TensorBoard reads: TFRecord-framed Event protos.
Both the protobuf wire encoding (only the handful of fields scalar
summaries need) and the masked-crc32c record framing are hand-rolled
here — ~100 lines replacing the reference's summary-writer C++ stack
for the scalar/text cases that matter for training loops.

    writer = SummaryWriter(logdir)
    writer.scalar("loss", 0.31, step=100)
    writer.flush()

Gauges (≙ tf.monitoring.*Gauge) are process-local observability cells;
``strategy_gauge`` records which strategy class is active, matching the
reference's distribution-strategy usage gauges (distribute_lib.py:190).
"""

from __future__ import annotations

import os
import struct
import threading
import time


# ---------------------------------------------------------------------------
# protobuf wire encoding (just what Event/Summary need)
# ---------------------------------------------------------------------------

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _len_delim(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def _double(field: int, value: float) -> bytes:
    return _tag(field, 1) + struct.pack("<d", value)


def _float(field: int, value: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", value)


def _int64(field: int, value: int) -> bytes:
    return _tag(field, 0) + _varint(value & 0xFFFFFFFFFFFFFFFF)


def _encode_scalar_event(tag: str, value: float, step: int,
                         wall_time: float) -> bytes:
    # Summary.Value { tag=1, simple_value=2 }
    sval = _len_delim(1, tag.encode()) + _float(2, value)
    # Summary { value=1 (repeated) }
    summary = _len_delim(1, sval)
    # Event { wall_time=1 (double), step=2 (int64), summary=5 }
    return _double(1, wall_time) + _int64(2, step) + _len_delim(5, summary)


def _encode_file_version(wall_time: float) -> bytes:
    # Event { wall_time=1, file_version=3 (string) }
    return _double(1, wall_time) + _len_delim(3, b"brain.Event:2")


def _packed_doubles(field: int, values) -> bytes:
    payload = b"".join(struct.pack("<d", float(v)) for v in values)
    return _len_delim(field, payload)


def _encode_histogram_event(tag: str, values, step: int,
                            wall_time: float, bins: int = 30) -> bytes:
    """Event carrying a HistogramProto (≙ tf.summary.histogram v1 wire
    format, which TensorBoard's histograms/distributions dashboards read).

    HistogramProto { min=1, max=2, num=3, sum=4, sum_squares=5,
                     bucket_limit=6 (packed double), bucket=7 } —
    bucket_limit[i] is the INCLUSIVE upper edge of bucket i.
    """
    import numpy as np
    arr = np.asarray(values, dtype=np.float64).ravel()
    # Log the finite subset: a diverging model (NaN/Inf weights) is
    # exactly when users turn on histograms, and np.histogram raises on
    # a non-finite range.
    arr = arr[np.isfinite(arr)]
    if arr.size == 0:
        arr = np.zeros((1,))
    lo, hi = float(arr.min()), float(arr.max())
    if lo == hi:                       # single-value histogram
        edges = np.array([lo, lo + 1e-12])
        counts = np.array([float(arr.size)])
    else:
        counts, edges = np.histogram(arr, bins=bins)
        counts = counts.astype(np.float64)
    histo = (_double(1, lo) + _double(2, hi)
             + _double(3, float(arr.size)) + _double(4, float(arr.sum()))
             + _double(5, float(np.square(arr).sum()))
             + _packed_doubles(6, edges[1:]) + _packed_doubles(7, counts))
    # Summary.Value { tag=1, histo=5 }
    sval = _len_delim(1, tag.encode()) + _len_delim(5, histo)
    summary = _len_delim(1, sval)
    return _double(1, wall_time) + _int64(2, step) + _len_delim(5, summary)


# ---------------------------------------------------------------------------
# TFRecord framing with masked crc32c
# ---------------------------------------------------------------------------

_CRC_TABLE = []


def _make_crc_table():
    poly = 0x82F63B78          # Castagnoli, reflected
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        _CRC_TABLE.append(crc)


_make_crc_table()


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


def tfrecord_frame(payload: bytes) -> bytes:
    """Frame one payload in TFRecord format (length + masked crc32c +
    payload + masked crc32c). Public: also used by the native input
    layer's TFRecord writer (input/native_loader.write_tfrecords)."""
    header = struct.pack("<Q", len(payload))
    return (header + struct.pack("<I", _masked_crc(header))
            + payload + struct.pack("<I", _masked_crc(payload)))


_tfrecord = tfrecord_frame   # internal alias


# ---------------------------------------------------------------------------
# Writer
# ---------------------------------------------------------------------------

class _SharedEventFile:
    """One physical event file, shared by every SummaryWriter a process
    opens on the same (logdir, suffix). Two writers created within the
    same wall second used to collide on the timestamped file name with
    independent handles — interleaved TFRecord frames through separate
    buffers tear the file. One handle per process + one lock makes
    concurrent writers safe by construction."""

    def __init__(self, path: str):
        self.path = path
        self.lock = threading.Lock()
        self.refs = 0
        self._f = open(path, "ab")
        self.write(_encode_file_version(time.time()))

    def write(self, event: bytes):
        with self.lock:
            self._f.write(_tfrecord(event))

    def flush(self):
        with self.lock:
            self._f.flush()

    def close_handle(self):
        with self.lock:
            self._f.flush()
            self._f.close()


class SummaryWriter:
    """Append-only scalar summary writer (TensorBoard event file).

    Concurrency: all writers a process opens on the same ``logdir`` (and
    suffix) share ONE file handle with locked, whole-frame writes; use
    as a context manager or call :meth:`close` when done.
    """

    _OPEN: "dict[tuple[str, str], _SharedEventFile]" = {}
    _OPEN_LOCK = threading.Lock()

    def __init__(self, logdir: str, filename_suffix: str = ""):
        os.makedirs(logdir, exist_ok=True)
        key = (os.path.realpath(logdir), filename_suffix)
        with SummaryWriter._OPEN_LOCK:
            shared = SummaryWriter._OPEN.get(key)
            if shared is None:
                fname = (f"events.out.tfevents.{int(time.time())}."
                         f"{os.uname().nodename}.{os.getpid()}"
                         f"{filename_suffix}")
                shared = _SharedEventFile(os.path.join(logdir, fname))
                SummaryWriter._OPEN[key] = shared
            shared.refs += 1
        self._key = key
        self._shared = shared
        self._closed = False
        self.path = shared.path

    def _write(self, event: bytes):
        if self._closed:
            raise ValueError(f"SummaryWriter for {self.path} is closed")
        self._shared.write(event)

    def scalar(self, tag: str, value: float, step: int,
               wall_time: float | None = None):
        self._write(_encode_scalar_event(
            tag, float(value), int(step),
            time.time() if wall_time is None else wall_time))

    def scalars(self, values: dict, step: int):
        for tag, v in values.items():
            self.scalar(tag, v, step)

    def histogram(self, tag: str, values, step: int,
                  wall_time: float | None = None, bins: int = 30):
        """Histogram summary (≙ tf.summary.histogram): weight/gradient
        distributions for TensorBoard's histograms dashboard."""
        self._write(_encode_histogram_event(
            tag, values, int(step),
            time.time() if wall_time is None else wall_time, bins=bins))

    def flush(self):
        self._shared.flush()

    def close(self):
        """Release this writer's reference; the underlying file handle
        closes when the last writer on the (logdir, suffix) closes.
        Idempotent."""
        if self._closed:
            return
        self._closed = True
        with SummaryWriter._OPEN_LOCK:
            self._shared.refs -= 1
            last = self._shared.refs == 0
            if last:
                SummaryWriter._OPEN.pop(self._key, None)
        if last:
            self._shared.close_handle()
        else:
            self._shared.flush()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# ---------------------------------------------------------------------------
# Monitoring gauges (≙ tf.monitoring — process-local observability)
# ---------------------------------------------------------------------------

class Gauge:
    """Named cell set to the latest value (≙ monitoring.StringGauge).

    Also exported through the unified telemetry MetricsRegistry (under
    ``monitoring<name>``), so tf.monitoring-style gauges appear in
    registry snapshots and cross-host fleet rollups.
    """

    kind = "gauge"

    _REGISTRY: dict = {}
    _LOCK = threading.Lock()

    def __init__(self, name: str, description: str = ""):
        self.name = name
        self.description = description
        self._value = None
        with Gauge._LOCK:
            Gauge._REGISTRY[name] = self
        from distributed_tensorflow_tpu.telemetry import registry as _treg
        _treg.get_registry().register(self, f"monitoring{name}"
                                      if name.startswith("/")
                                      else f"monitoring/{name}")

    def set(self, value):
        self._value = value

    def value(self):
        return self._value

    def export(self) -> dict:
        return {"type": "gauge", "value": self._value}

    @classmethod
    def all_gauges(cls) -> dict:
        with cls._LOCK:
            return {k: g.value() for k, g in cls._REGISTRY.items()}


# ---------------------------------------------------------------------------
# Reading event files back (the reverse of the writer above): scalar
# series for tests and tools/obs_report.py — no TensorBoard dependency.
# ---------------------------------------------------------------------------

def _decode_fields(buf: bytes):
    """Iterate (field_number, wire_type, value) over one proto message.
    value is raw bytes for len-delimited fields, int for varint/fixed."""
    i, n = 0, len(buf)
    while i < n:
        tag = 0
        shift = 0
        while True:
            b = buf[i]
            i += 1
            tag |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        field, wire = tag >> 3, tag & 7
        if wire == 0:                     # varint
            v = 0
            shift = 0
            while True:
                b = buf[i]
                i += 1
                v |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            yield field, wire, v
        elif wire == 1:                   # fixed64
            yield field, wire, buf[i:i + 8]
            i += 8
        elif wire == 2:                   # len-delimited
            ln = 0
            shift = 0
            while True:
                b = buf[i]
                i += 1
                ln |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            yield field, wire, buf[i:i + ln]
            i += ln
        elif wire == 5:                   # fixed32
            yield field, wire, buf[i:i + 4]
            i += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")


def read_event_records(path: str):
    """Iterate raw TFRecord payloads from an event file, verifying the
    masked crc32c of each frame."""
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                return
            (length,) = struct.unpack("<Q", header)
            (hcrc,) = struct.unpack("<I", f.read(4))
            if _masked_crc(header) != hcrc:
                raise ValueError(f"{path}: corrupt record header")
            payload = f.read(length)
            (pcrc,) = struct.unpack("<I", f.read(4))
            if len(payload) < length or _masked_crc(payload) != pcrc:
                raise ValueError(f"{path}: corrupt record payload")
            yield payload


def read_scalars(path: str) -> "list[tuple[str, int, float]]":
    """All scalar summaries in an event file as (tag, step, value)."""
    out = []
    for payload in read_event_records(path):
        step = 0
        summary = None
        for field, wire, v in _decode_fields(payload):
            if field == 2 and wire == 0:          # Event.step
                step = v
            elif field == 5 and wire == 2:        # Event.summary
                summary = v
        if summary is None:
            continue
        for field, wire, v in _decode_fields(summary):
            if field != 1 or wire != 2:
                continue                          # Summary.value entries
            tag, value = None, None
            for f2, w2, v2 in _decode_fields(v):
                if f2 == 1 and w2 == 2:
                    tag = v2.decode("utf-8", "replace")
                elif f2 == 2 and w2 == 5:
                    (value,) = struct.unpack("<f", v2)
            if tag is not None and value is not None:
                out.append((tag, step, value))
    return out


# ≙ distribute_lib.py:190 distribution_strategy_gauge: records which
# strategy the process is using (set by Strategy.scope).
strategy_gauge = Gauge("/tensorflow/api/distribution_strategy",
                       "active tf.distribute strategy class")
api_gauge = Gauge("/tensorflow/api/distribution_strategy/api",
                  "last distribution API used (scope/run/reduce)")
