"""Compatibility shims for older jax runtimes.

The framework targets current jax (`jax.shard_map`, `check_vma`,
`jax.sharding.AxisType`), but deployment containers sometimes pin an
older jaxlib. Rather than gating every call site, `install()` — called
once from the package `__init__` — backfills the missing surface when
(and only when) it is absent:

- ``jax.shard_map``: aliased from ``jax.experimental.shard_map``, with
  the ``check_vma`` kwarg translated to its old name ``check_rep``;
- ``jax.lax.axis_size``: emulated with ``psum(1, name)``, which
  constant-folds to the static axis size under tracing on old jax.

Version-sensitive sites that need more than an alias do their own
feature detection in place (``cluster/topology.py`` for ``AxisType``,
``cluster/coordination.py`` for the coordination-client vintage).
"""

from __future__ import annotations

import functools

import jax


def install():
    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map

        @functools.wraps(_shard_map)
        def shard_map(f, **kwargs):
            if "check_vma" in kwargs:
                kwargs["check_rep"] = kwargs.pop("check_vma")
            return _shard_map(f, **kwargs)

        jax.shard_map = shard_map
    if not hasattr(jax.lax, "axis_size"):
        jax.lax.axis_size = lambda name: jax.lax.psum(1, name)
