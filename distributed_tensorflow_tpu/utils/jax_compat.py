"""Compatibility shims for older jax runtimes.

The framework targets current jax (`jax.shard_map`, `check_vma`,
`jax.sharding.AxisType`), but deployment containers sometimes pin an
older jaxlib. Rather than gating every call site, `install()` — called
once from the package `__init__` — backfills the missing surface when
(and only when) it is absent:

- ``jax.shard_map``: aliased from ``jax.experimental.shard_map``, with
  the ``check_vma`` kwarg translated to its old name ``check_rep``;
- ``jax.lax.axis_size``: emulated with ``psum(1, name)``, which
  constant-folds to the static axis size under tracing on old jax.

Version-sensitive sites that need more than an alias do their own
feature detection in place (``cluster/topology.py`` for ``AxisType``,
``cluster/coordination.py`` for the coordination-client vintage).
"""

from __future__ import annotations

import functools
import os

import jax


def safe_donate_argnums(argnums: tuple) -> tuple:
    """``donate_argnums`` value that is safe on this jax vintage.

    jax<=0.4.37 (probed via the missing ``jax.sharding.AxisType``, the
    repo's standard vintage gate): an executable DESERIALIZED from the
    persistent compilation cache mis-applies input-output aliasing for
    donated sharded CPU programs — outputs that should carry fresh
    values read back as the (dead) donated input buffer, and repeated
    host reads of the same output disagree. Root-caused in ISSUE 4 from
    the ``test_resnet_via_fit_under_tpu_strategy`` flake: BN batch_stats
    froze exactly when conftest's persistent cache had the entry
    (first-ever run compiles fresh and passes; every warm run fails).
    Minimal repro: jit(donate_argnums=0) over NamedSharding state +
    ``jnp.where`` carry, 8 virtual CPU devices — run twice with
    JAX_COMPILATION_CACHE_DIR set.

    Donation is disabled ONLY in the unsafe configuration (legacy
    vintage AND persistent cache active) — TPU/real runs keep the HBM
    saving.
    """
    if hasattr(jax.sharding, "AxisType"):
        return argnums
    cache_dir = None
    try:
        cache_dir = jax.config.jax_compilation_cache_dir
    except AttributeError:
        pass
    cache_dir = cache_dir or os.environ.get("JAX_COMPILATION_CACHE_DIR")
    return () if cache_dir else argnums


def install():
    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map

        @functools.wraps(_shard_map)
        def shard_map(f, **kwargs):
            if "check_vma" in kwargs:
                kwargs["check_rep"] = kwargs.pop("check_vma")
            return _shard_map(f, **kwargs)

        jax.shard_map = shard_map
    if not hasattr(jax.lax, "axis_size"):
        jax.lax.axis_size = lambda name: jax.lax.psum(1, name)
