"""Utilities: profiling/tracing, monitoring gauges, debugging helpers."""
