"""Profiling and tracing (≙ tf.profiler surface, SURVEY.md §5.1).

Maps the reference's profiler API onto jax.profiler, which shares the
same XPlane/TraceMe backend (both sit on tsl/profiler):

- ``start(logdir)`` / ``stop()``           ≙ tf.profiler.experimental.start/stop
  (reference: tensorflow/python/profiler/profiler_v2.py:81/:130)
- ``Trace("name")`` scoped annotation      ≙ tf.profiler.experimental.Trace
  (reference trace.py:28; native TraceMe)
- ``start_server(port)`` on each worker +
  ``trace(service_addr, logdir)`` from a
  client                                   ≙ remote/pod profiling
  (reference profiler_v2.py:169 + profiler_client.py) — the multi-host
  TPU profiling shape is kept identical.
- ``annotate_function``                    decorator form of Trace.

Output is XPlane protos under ``<logdir>/plugins/profile/<run>``, viewable
with tensorboard_plugin_profile or xprof — the same toolchain the
reference's traces feed.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import threading
import time
import weakref

import jax


@dataclasses.dataclass(frozen=True)
class ProfilerOptions:
    """≙ tf.profiler.experimental.ProfilerOptions (profiler_v2.py:46).

    XLA/JAX's profiler always records host + device + python trace
    levels; the fields are accepted for API parity and the meaningful
    one (``python_tracer_level``) toggles jax's python tracer.
    """
    host_tracer_level: int = 2
    python_tracer_level: int = 1
    device_tracer_level: int = 1
    delay_ms: int = 0


_state = threading.local()


def start(logdir: str, options: ProfilerOptions | None = None) -> None:
    """Start collecting a trace on this host (device + host + python)."""
    options = options or ProfilerOptions()
    create_perfetto = False
    jax.profiler.start_trace(
        logdir,
        create_perfetto_link=create_perfetto,
        create_perfetto_trace=create_perfetto)
    _state.active_logdir = logdir


def stop() -> None:
    """Stop tracing and write the XPlane output."""
    jax.profiler.stop_trace()
    _state.active_logdir = None


@contextlib.contextmanager
def profile(logdir: str, options: ProfilerOptions | None = None):
    start(logdir, options)
    try:
        yield
    finally:
        stop()


class Trace(jax.profiler.TraceAnnotation):
    """Scoped trace annotation visible in the trace viewer.

    ≙ tf.profiler.experimental.Trace (trace.py:28). Usage:

        with Trace("train_step", step_num=i):
            state, metrics = step(state, batch)
    """

    def __init__(self, name: str, **kwargs):
        if kwargs:
            name = name + " " + " ".join(
                f"{k}={v}" for k, v in sorted(kwargs.items()))
        super().__init__(name)


def annotate_function(fn=None, *, name: str | None = None):
    """Decorator: annotate every call of ``fn`` in the profile."""
    if fn is None:
        return functools.partial(annotate_function, name=name)
    label = name or getattr(fn, "__name__", "fn")

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with Trace(label):
            return fn(*args, **kwargs)
    return wrapper


def start_server(port: int):
    """Start the on-demand profiling server on this worker (every host of
    a pod job calls this; a client then requests traces remotely).
    ≙ tf.profiler.experimental.server.start (profiler_v2.py:169)."""
    return jax.profiler.start_server(port)


def stop_server():
    jax.profiler.stop_server()


def _profile_here(logdir: str, duration_ms: int) -> str:
    """Run an on-host profiling session in THIS process (executed on the
    target via remote dispatch)."""
    import time as _time
    import jax as _jax
    with _jax.profiler.trace(logdir):
        _time.sleep(duration_ms / 1000.0)
    return logdir


def trace(target, logdir: str, duration_ms: int = 2000,
          host_tracer_level: int = 2, num_tracing_attempts: int = 1):
    """Collect ``duration_ms`` of profile from ``target`` into ``logdir``.

    ≙ tf.profiler.experimental.client.trace (profiler_client.py), with a
    TPU-native transport: instead of the reference's grpc ProfilerService
    client (a TensorFlow runtime dependency this framework does not
    take), remote collection rides the framework's own control plane —
    the profiling closure is dispatched to the target PROCESS over the
    coordination service (coordinator/remote_dispatch.py; the target must
    run ``remote_dispatch.run_worker_loop``). Traces land in ``logdir``
    (shared filesystem), viewable in TensorBoard/XProf like the
    reference's.

    ``target``: "local"/None = this process; an int = remote process id.
    ``host_tracer_level`` is accepted for reference-API parity (the jax
    session traces host activity at its standard level);
    ``num_tracing_attempts`` retries transient failures.
    """
    del host_tracer_level           # parity knob; jax session default
    last_err = None
    for _ in range(max(1, num_tracing_attempts)):
        try:
            if target in (None, "local"):
                return _profile_here(logdir, duration_ms)
            if isinstance(target, int):
                from distributed_tensorflow_tpu.coordinator \
                    .remote_dispatch import RemoteLane
                return RemoteLane(target).execute(
                    _profile_here, (logdir, duration_ms), {},
                    timeout_s=duration_ms / 1000.0 + 60.0)
            break
        except (RuntimeError, TimeoutError) as e:
            last_err = e
    if last_err is not None:
        raise last_err
    raise TypeError(
        f"target must be 'local' or a process id, got {target!r}; "
        f"address-based collection would need a grpc ProfilerService "
        f"client, which the TPU-native runtime deliberately does not "
        f"depend on")


@contextlib.contextmanager
def step_marker(step: int):
    """Mark a training step boundary (StepMarker shows step time in the
    trace viewer's overview page).

    **Step-number correlation contract:** the ``step_num`` recorded here
    (and by ``Trace("...", step_num=i)`` annotations) is the SAME
    integer the telemetry layer carries — ``StepTelemetry.
    step_completed(step)`` / the ``step`` field of ``train.step`` JSONL
    events. When telemetry is on, the marker additionally emits a
    ``profiler.step_marker`` event stamped with that step, so an XPlane
    trace (this module's output) and the framework timeline
    (``tools/trace_report.py``'s output) can be lined up step-by-step
    even though they come from different clocks. Regression-tested in
    tests/test_profiler.py.
    """
    from distributed_tensorflow_tpu import telemetry as _telemetry
    _telemetry.event("profiler.step_marker", step=int(step))
    with jax.profiler.StepTraceAnnotation("train", step_num=step):
        yield


# ---------------------------------------------------------------------------
# Op-profile analysis: read the collected XPlane back into a per-op table
# (≙ the op_profile view of tensorboard_plugin_profile, which cannot load
# in every environment — this gives the same answer as a plain API).
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class OpTime:
    name: str          # HLO op name (truncated to the metadata string)
    total_ms: float    # summed device time across the collected trace
    fraction: float    # share of total device op time
    count: int         # number of trace events


def _load_xspace(logdir: str):
    """Locate and parse the newest ``*.xplane.pb`` under ``logdir``."""
    import glob
    import os
    paths = sorted(glob.glob(
        os.path.join(logdir, "plugins", "profile", "*", "*.xplane.pb")))
    if not paths:
        raise FileNotFoundError(
            f"no xplane.pb under {logdir}/plugins/profile — call "
            f"profiler.start/stop (or profiler.trace) first")
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except Exception as e:                      # pragma: no cover
        raise ImportError(
            "op_profile needs the xplane proto bindings (shipped with "
            "tensorflow); install tensorflow or read the raw trace with "
            f"xprof: {e}") from e
    xs = xplane_pb2.XSpace()
    with open(paths[-1], "rb") as f:
        xs.ParseFromString(f.read())
    return xs


def op_profile(logdir: str, top: int = 20,
               device_substr: str = "TPU") -> "list[OpTime]":
    """Aggregate device op time from a collected trace.

    Returns the ``top`` ops by total device time on the first device
    plane matching ``device_substr`` (line "XLA Ops" — the serialized
    op timeline). Use after ``profiler.profile(logdir)``::

        with profiler.profile("/tmp/prof"):
            train_step(...)
        for row in profiler.op_profile("/tmp/prof"):
            print(f"{row.total_ms:8.2f}ms {row.fraction:5.1%} {row.name}")
    """
    xs = _load_xspace(logdir)
    from collections import defaultdict
    for plane in xs.planes:
        if device_substr not in plane.name:
            continue
        emeta = {k: m.name for k, m in plane.event_metadata.items()}
        # TPU device planes carry a serialized "XLA Ops" timeline; the
        # CPU backend instead records per-thread executor lines
        # (tf_xla-cpu-codegen/...). Prefer the former, fall back to the
        # latter so the same call works against the CPU test backend.
        lines = [ln for ln in plane.lines if ln.name == "XLA Ops"]
        if not lines:
            lines = [ln for ln in plane.lines
                     if ln.name.lower().startswith("tf_xla")]
        tot = defaultdict(lambda: [0, 0])
        for line in lines:
            for ev in line.events:
                cell = tot[emeta.get(ev.metadata_id, "?")]
                cell[0] += ev.duration_ps
                cell[1] += 1
        if not tot:
            continue
        total_ps = sum(v[0] for v in tot.values()) or 1
        rows = [OpTime(name=name, total_ms=ps / 1e9,
                       fraction=ps / total_ps, count=n)
                for name, (ps, n) in tot.items()]
        rows.sort(key=lambda r: -r.total_ms)
        return rows[:top]
    raise ValueError(
        f"no plane matching {device_substr!r} with XLA op events found "
        f"(planes: {[p.name for p in xs.planes]})")


# ---------------------------------------------------------------------------
# Host input-pipeline telemetry (≙ tf.data's iterator/autotune stats,
# TF/python/data/experimental/ops/stats_ops.py): every concurrent pipeline
# stage (parallel map/interleave, prefetch, infeed) owns a StageStats and
# registers it here, so the bottleneck stage is attributable from counters
# instead of guessed. The four wait channels answer the only question that
# matters — WHO is blocking WHOM:
#
# - ``busy_s``          time the stage spent doing its own work (map fn,
#                       decode, upstream next() for prefetch)
# - ``producer_wait_s`` stage blocked pulling from upstream (upstream is
#                       the bottleneck)
# - ``blocked_put_s``   stage blocked handing off downstream (downstream
#                       is the bottleneck; bounded queue full)
# - ``consumer_wait_s`` the CONSUMER blocked on this stage (THIS stage is
#                       the bottleneck)
# ---------------------------------------------------------------------------

_stage_registry: "list[weakref.ref]" = []
_stage_lock = threading.Lock()


class StageStats:
    """Thread-safe counters for one concurrent pipeline stage."""

    def __init__(self, name: str, *, workers: int | None = None,
                 register: bool = True):
        self.name = name
        self.workers = workers
        self._lock = threading.Lock()
        self._elements = 0
        self._busy_s = 0.0
        self._producer_wait_s = 0.0
        self._blocked_put_s = 0.0
        self._consumer_wait_s = 0.0
        self._queue_depth_sum = 0
        self._queue_samples = 0
        self._first_t: float | None = None
        self._last_t: float | None = None
        if register:
            register_stage(self)

    def record(self, *, elements: int = 0, busy_s: float = 0.0,
               producer_wait_s: float = 0.0, blocked_put_s: float = 0.0,
               consumer_wait_s: float = 0.0,
               queue_depth: int | None = None) -> None:
        now = time.monotonic()
        with self._lock:
            if self._first_t is None:
                self._first_t = now
            self._last_t = now
            self._elements += elements
            self._busy_s += busy_s
            self._producer_wait_s += producer_wait_s
            self._blocked_put_s += blocked_put_s
            self._consumer_wait_s += consumer_wait_s
            if queue_depth is not None:
                self._queue_depth_sum += queue_depth
                self._queue_samples += 1

    def snapshot(self) -> dict:
        with self._lock:
            wall = ((self._last_t - self._first_t)
                    if self._first_t is not None else 0.0)
            return {
                "name": self.name,
                "workers": self.workers,
                "elements": self._elements,
                "busy_s": round(self._busy_s, 6),
                "producer_wait_s": round(self._producer_wait_s, 6),
                "blocked_put_s": round(self._blocked_put_s, 6),
                "consumer_wait_s": round(self._consumer_wait_s, 6),
                "mean_queue_depth": (
                    round(self._queue_depth_sum / self._queue_samples, 3)
                    if self._queue_samples else None),
                "elements_per_sec": (
                    round(self._elements / wall, 2) if wall > 0 else None),
            }


def register_stage(stats: StageStats) -> None:
    """Add a stage to the process-wide telemetry registry (weakly held —
    an abandoned pipeline's stages disappear with it)."""
    with _stage_lock:
        _stage_registry.append(weakref.ref(stats))


def pipeline_stats(prefix: str | None = None) -> "list[dict]":
    """Snapshots of every live registered stage, registration order.
    ``prefix`` filters on the stage name (e.g. ``"map"``)."""
    out = []
    with _stage_lock:
        live = []
        for ref in _stage_registry:
            s = ref()
            if s is not None:
                live.append(ref)
                if prefix is None or s.name.startswith(prefix):
                    out.append(s.snapshot())
        _stage_registry[:] = live
    return out


def clear_pipeline_stats() -> None:
    """Drop all registered stages (test isolation)."""
    with _stage_lock:
        _stage_registry.clear()


def bottleneck_stage() -> dict | None:
    """The stage its consumer waited on the longest — the pipeline's
    measured bottleneck (None when nothing is registered)."""
    snaps = pipeline_stats()
    if not snaps:
        return None
    return max(snaps, key=lambda s: s["consumer_wait_s"])


# -- telemetry bridge -------------------------------------------------------
# Every live pipeline stage (input/dataset.py map/interleave/prefetch,
# training/loops.py infeed) exports through the unified MetricsRegistry:
# registry snapshots — and therefore cross-host fleet rollups and
# tools/obs_report.py — carry the input pipeline's counters without the
# stages giving up their own (weakly-registered) storage.

def _pipeline_collector() -> dict:
    out = {}
    for snap in pipeline_stats():
        stage = snap.get("name", "?")
        for k, v in snap.items():
            if k in ("name", "workers") or v is None:
                continue
            out[f"{stage}/{k}"] = v
    return out


def _register_telemetry_collector():
    from distributed_tensorflow_tpu.telemetry import registry as _treg
    _treg.get_registry().register_collector("input/pipeline",
                                            _pipeline_collector)


_register_telemetry_collector()
