"""Tensor Tracer: numerics inspection inside compiled programs.

≙ tensorflow/python/tpu/tensor_tracer.py (2,314 LoC + flags + report —
SURVEY.md §2.6): the reference instruments every op in a TPU graph and
streams per-tensor statistics (norm / max / min / NaN counts) to a trace
report for debugging silent numerical corruption on device.

TPU-native design — two complementary instruments:

- :func:`trace_point` — explicit markers inside ANY jitted/SPMD code.
  Stats (norm, max, min, nan/inf counts) are computed ON DEVICE (a few
  scalar reductions, negligible next to the surrounding matmuls) and
  delivered to the host collector via ``jax.debug.callback`` — the
  analogue of the reference's outfeed-streamed trace events.
- :func:`trace_flax` — zero-annotation capture for flax models: runs
  ``capture_intermediates`` and reduces every intermediate to the same
  statistics, returning a :class:`TraceReport` (≙ tensor_tracer_report's
  per-tensor table) that can locate e.g. the first NaN-producing module.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

_TRACE_MODES = ("norm", "max-abs", "nan-count", "summary")


def _stats(x) -> dict:
    """The per-tensor statistic bundle (≙ trace_mode=summary)."""
    x = jnp.asarray(x)
    xf = x.astype(jnp.float32)
    return {
        "norm": jnp.linalg.norm(xf.ravel()),
        "max": jnp.max(xf) if x.size else jnp.float32(0),
        "min": jnp.min(xf) if x.size else jnp.float32(0),
        "mean": jnp.mean(xf) if x.size else jnp.float32(0),
        "nan_count": jnp.sum(jnp.isnan(xf)),
        "inf_count": jnp.sum(jnp.isinf(xf)),
    }


class _Collector:
    """Process-global (NOT thread-local: debug callbacks may run on
    runtime threads, not the thread that entered the tracer)."""

    def __init__(self):
        self.events: list[tuple[str, dict]] = []
        self.active = False
        self.lock = threading.Lock()


_COLLECTOR = _Collector()


def trace_point(name: str, x, *, enabled: bool | None = None):
    """Record numerics stats for ``x`` under ``name``; returns ``x``
    unchanged (insert anywhere in jitted code, like the reference's
    per-op instrumentation but opt-in). No-op unless inside a
    :class:`TensorTracer` context (or ``enabled=True``)."""
    if enabled is None:
        enabled = _COLLECTOR.active
    if not enabled:
        return x
    stats = _stats(x)

    def record(**host_stats):
        # instrumentation is baked at TRACE time; collection is gated at
        # CALL time (a compiled fn may outlive the tracer context)
        with _COLLECTOR.lock:
            if _COLLECTOR.active:
                _COLLECTOR.events.append(
                    (name, {k: np.asarray(v).item()
                            for k, v in host_stats.items()}))

    jax.debug.callback(record, **stats)
    return x


@dataclasses.dataclass
class TraceReport:
    """Per-tensor statistics table (≙ tensor_tracer_report.py)."""
    entries: list  # [(name, {stat: float})]

    def nan_entries(self) -> list:
        return [(n, s) for n, s in self.entries
                if s.get("nan_count", 0) > 0 or s.get("inf_count", 0) > 0]

    def first_nan(self) -> "str | None":
        bad = self.nan_entries()
        return bad[0][0] if bad else None

    def __str__(self):
        lines = [f"{'tensor':50s} {'norm':>12s} {'max':>12s} "
                 f"{'nan':>6s} {'inf':>6s}"]
        for name, s in self.entries:
            lines.append(
                f"{name[:50]:50s} {s['norm']:12.4e} {s['max']:12.4e} "
                f"{int(s['nan_count']):6d} {int(s['inf_count']):6d}")
        return "\n".join(lines)


class TensorTracer:
    """Collects :func:`trace_point` events (≙ the tensor_tracer session).

        tt = TensorTracer()
        with tt:
            jitted_step(state, batch)     # fns containing trace_point
        print(tt.report())
    """

    def __enter__(self):
        with _COLLECTOR.lock:
            _COLLECTOR.events = []
            _COLLECTOR.active = True
        return self

    def __exit__(self, *exc):
        # async dispatch: callbacks may still be in flight — drain them
        # BEFORE deactivating or they'd be silently dropped
        jax.effects_barrier()
        with _COLLECTOR.lock:
            _COLLECTOR.active = False
        return False

    def report(self) -> TraceReport:
        # callbacks are async: drain outstanding work first
        jax.effects_barrier()
        with _COLLECTOR.lock:
            return TraceReport(list(_COLLECTOR.events))


def trace_flax(module, variables, *args, mutable=False,
               **kwargs) -> tuple[Any, TraceReport]:
    """Run a flax module capturing EVERY intermediate's numerics
    (≙ full-graph tracing, trace_mode=summary). Returns
    (outputs, TraceReport) with one entry per module call site.
    """
    out, state = module.apply(
        variables, *args, capture_intermediates=True,
        mutable=["intermediates"] if mutable is False
        else list(mutable) + ["intermediates"], **kwargs)
    inter = state["intermediates"]
    entries = []

    def walk(tree, prefix):
        if isinstance(tree, dict):
            for k in sorted(tree):
                walk(tree[k], f"{prefix}/{k}" if prefix else k)
        elif isinstance(tree, (tuple, list)):
            for i, v in enumerate(tree):
                walk(v, f"{prefix}[{i}]" if len(tree) > 1 else prefix)
        elif hasattr(tree, "shape"):
            entries.append(
                (prefix, {k: float(np.asarray(v))
                          for k, v in _stats(tree).items()}))

    walk(jax.tree_util.tree_map(lambda x: x, inter,
                                is_leaf=lambda x: hasattr(x, "shape")), "")
    return out, TraceReport(entries)


def find_first_nan(module, variables, *args, **kwargs) -> "str | None":
    """Locate the first module call site producing NaN/Inf
    (the reference's headline debugging use case)."""
    _, report = trace_flax(module, variables, *args, **kwargs)
    return report.first_nan()
