"""Tensor Tracer: numerics inspection inside compiled programs.

≙ tensorflow/python/tpu/tensor_tracer.py (2,314 LoC + flags + report —
SURVEY.md §2.6): the reference instruments every op in a TPU graph and
streams per-tensor statistics (norm / max / min / NaN counts) to a trace
report for debugging silent numerical corruption on device.

TPU-native design — two complementary instruments:

- :func:`trace_point` — explicit markers inside ANY jitted/SPMD code.
  Stats (norm, max, min, nan/inf counts) are computed ON DEVICE (a few
  scalar reductions, negligible next to the surrounding matmuls) and
  delivered to the host collector via ``jax.debug.callback`` — the
  analogue of the reference's outfeed-streamed trace events.
- :func:`trace_flax` — zero-annotation capture for flax models: runs
  ``capture_intermediates`` and reduces every intermediate to the same
  statistics, returning a :class:`TraceReport` (≙ tensor_tracer_report's
  per-tensor table) that can locate e.g. the first NaN-producing module.
- :func:`instrument` / :func:`trace_fn` — WHOLE-PROGRAM instrumentation
  of any jittable function, no annotations required (≙ the reference's
  per-op graph rewrite, tensor_tracer.py:1431 ``trace``): the function's
  jaxpr is re-traced with the stats bundle attached to EVERY equation's
  outputs (recursing through jit/remat/custom-grad sub-jaxprs), each
  entry named by primitive + source line. Filterable by op-type/name
  regex (≙ --trace_mode/--included_ops flags), report writable to a
  file (≙ tensor_tracer_report.py), ``TraceReport.first_nan()`` is the
  first-NaN localizer.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

_TRACE_MODES = ("norm", "max-abs", "nan-count", "summary")


def _stats(x) -> dict:
    """The per-tensor statistic bundle (≙ trace_mode=summary)."""
    x = jnp.asarray(x)
    xf = x.astype(jnp.float32)
    return {
        "norm": jnp.linalg.norm(xf.ravel()),
        "max": jnp.max(xf) if x.size else jnp.float32(0),
        "min": jnp.min(xf) if x.size else jnp.float32(0),
        "mean": jnp.mean(xf) if x.size else jnp.float32(0),
        "nan_count": jnp.sum(jnp.isnan(xf)),
        "inf_count": jnp.sum(jnp.isinf(xf)),
    }


class _Collector:
    """Process-global (NOT thread-local: debug callbacks may run on
    runtime threads, not the thread that entered the tracer)."""

    def __init__(self):
        self.events: list[tuple[str, dict]] = []
        self.active = False
        self.lock = threading.Lock()


_COLLECTOR = _Collector()


def trace_point(name: str, x, *, enabled: bool | None = None,
                iteration=None):
    """Record numerics stats for ``x`` under ``name``; returns ``x``
    unchanged (insert anywhere in jitted code, like the reference's
    per-op instrumentation but opt-in). No-op unless inside a
    :class:`TensorTracer` context (or ``enabled=True``).

    ``iteration``: optional traced loop counter — entries from
    instrumented scan/while bodies carry it as an ``iteration`` stat so
    one body rewrite reports every trip (≙ the reference tagging trace
    events with the training step)."""
    if enabled is None:
        enabled = _COLLECTOR.active
    if not enabled:
        return x
    stats = _stats(x)
    if iteration is not None:
        stats["iteration"] = jnp.asarray(iteration, jnp.int32)

    def record(**host_stats):
        # instrumentation is baked at TRACE time; collection is gated at
        # CALL time (a compiled fn may outlive the tracer context)
        with _COLLECTOR.lock:
            if _COLLECTOR.active:
                _COLLECTOR.events.append(
                    (name, {k: np.asarray(v).item()
                            for k, v in host_stats.items()}))

    jax.debug.callback(record, **stats)
    return x


@dataclasses.dataclass
class TraceReport:
    """Per-tensor statistics table (≙ tensor_tracer_report.py)."""
    entries: list  # [(name, {stat: float})]

    def nan_entries(self) -> list:
        return [(n, s) for n, s in self.entries
                if s.get("nan_count", 0) > 0 or s.get("inf_count", 0) > 0]

    def first_nan(self) -> "str | None":
        bad = self.nan_entries()
        if not bad:
            return None
        name, stats = bad[0]
        if "iteration" in stats:
            return f"{name} [iteration {int(stats['iteration'])}]"
        return name

    def __str__(self):
        lines = [f"{'tensor':50s} {'norm':>12s} {'max':>12s} "
                 f"{'nan':>6s} {'inf':>6s}"]
        for name, s in self.entries:
            lines.append(
                f"{name[:50]:50s} {s['norm']:12.4e} {s['max']:12.4e} "
                f"{int(s['nan_count']):6d} {int(s['inf_count']):6d}")
        return "\n".join(lines)

    def write(self, path: str) -> str:
        """Write the per-tensor table to ``path`` (≙ the reference's
        trace report file, tensor_tracer_report.py ``create_report``)."""
        import os
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            f.write(str(self) + "\n")
            bad = self.first_nan()
            f.write(f"\nfirst_nan: {bad}\n" if bad
                    else "\nfirst_nan: none\n")
        return path


class TensorTracer:
    """Collects :func:`trace_point` events (≙ the tensor_tracer session).

        tt = TensorTracer()
        with tt:
            jitted_step(state, batch)     # fns containing trace_point
        print(tt.report())
    """

    def __enter__(self):
        with _COLLECTOR.lock:
            _COLLECTOR.events = []
            _COLLECTOR.active = True
        return self

    def __exit__(self, *exc):
        # async dispatch: callbacks may still be in flight — drain them
        # BEFORE deactivating or they'd be silently dropped
        jax.effects_barrier()
        with _COLLECTOR.lock:
            _COLLECTOR.active = False
        return False

    def report(self) -> TraceReport:
        # callbacks are async: drain outstanding work first
        jax.effects_barrier()
        with _COLLECTOR.lock:
            return TraceReport(list(_COLLECTOR.events))


def trace_flax(module, variables, *args, mutable=False,
               **kwargs) -> tuple[Any, TraceReport]:
    """Run a flax module capturing EVERY intermediate's numerics
    (≙ full-graph tracing, trace_mode=summary). Returns
    (outputs, TraceReport) with one entry per module call site.
    """
    out, state = module.apply(
        variables, *args, capture_intermediates=True,
        mutable=["intermediates"] if mutable is False
        else list(mutable) + ["intermediates"], **kwargs)
    inter = state["intermediates"]
    entries = []

    def walk(tree, prefix):
        if isinstance(tree, dict):
            for k in sorted(tree):
                walk(tree[k], f"{prefix}/{k}" if prefix else k)
        elif isinstance(tree, (tuple, list)):
            for i, v in enumerate(tree):
                walk(v, f"{prefix}[{i}]" if len(tree) > 1 else prefix)
        elif hasattr(tree, "shape"):
            entries.append(
                (prefix, {k: float(np.asarray(v))
                          for k, v in _stats(tree).items()}))

    walk(jax.tree_util.tree_map(lambda x: x, inter,
                                is_leaf=lambda x: hasattr(x, "shape")), "")
    return out, TraceReport(entries)


def find_first_nan(module, variables, *args, **kwargs) -> "str | None":
    """Locate the first module call site producing NaN/Inf
    (the reference's headline debugging use case)."""
    _, report = trace_flax(module, variables, *args, **kwargs)
    return report.first_nan()


# ---------------------------------------------------------------------------
# Whole-program jaxpr instrumentation (≙ tensor_tracer.py per-op rewrite)
# ---------------------------------------------------------------------------

# Call-like primitives whose sub-jaxpr is inlined and instrumented too.
# scan/while/cond get dedicated handling below: their BODIES are
# rewritten once into instrumented Python functions and re-staged
# through lax.scan/while_loop/switch, so every iteration reports per-
# equation stats tagged with a carried iteration counter (≙ the
# reference instrumenting the compiled program as-is — its TF graphs
# keep the while-loop and the instrumentation rides inside it).
_CALL_PRIMITIVES = {"jit", "pjit", "closed_call", "core_call",
                    "remat", "remat2", "checkpoint",
                    "custom_jvp_call", "custom_vjp_call",
                    "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr"}

_SKIP_PRIMITIVES = {"debug_callback"}      # don't trace our own probes


def _numeric_aval(aval) -> bool:
    try:
        return np.issubdtype(aval.dtype, np.number)
    except Exception:
        return False                       # PRNG keys, tokens, ...


def instrument(fn: Callable, *, op_regex: "str | None" = None,
               name_regex: "str | None" = None,
               max_traced: "int | None" = None) -> Callable:
    """Wrap ``fn`` so EVERY intermediate tensor is traced — no model
    annotations needed (≙ the reference instrumenting every op of the
    compiled TPU program, tensor_tracer.py:1431).

    The wrapper stages ``fn`` to a jaxpr, then re-traces it equation by
    equation, attaching the on-device stats bundle (via
    :func:`trace_point`) to each numeric output. jit/remat/custom-grad
    sub-jaxprs are entered recursively, and scan/while/cond bodies are
    rewritten ONCE and re-staged through lax.scan/while_loop/switch —
    every loop trip reports per-equation stats tagged with a carried
    ``iteration`` counter, so a ``scan_layers=True`` model gets per-op,
    per-LAYER coverage with no reconfiguration (the layer index IS the
    scan iteration). The result is itself jittable; run it under a
    :class:`TensorTracer` context to collect.

    ``op_regex`` filters by primitive name (≙ --included_ops),
    ``name_regex`` by the full entry name incl. source file:line,
    ``max_traced`` caps the number of instrumented equations.
    A train step CONTAINING ``jax.grad``/``value_and_grad`` instruments
    fine (the grad is resolved before staging, custom_vjp rules and
    all); what remains unsupported is differentiating the instrumented
    wrapper itself — instrument the whole train step instead.
    """
    import re as _re
    from jax._src import source_info_util

    op_re = _re.compile(op_regex) if op_regex else None
    name_re = _re.compile(name_regex) if name_regex else None
    from jax.extend import core as jexc

    def wrapped(*args, **kwargs):
        flat_args, in_tree = jax.tree_util.tree_flatten((args, kwargs))
        closed, out_shape = jax.make_jaxpr(
            lambda *a: fn(*jax.tree_util.tree_unflatten(in_tree, a)[0],
                          **jax.tree_util.tree_unflatten(in_tree, a)[1]),
            return_shape=True)(*flat_args)
        out_tree = jax.tree_util.tree_structure(out_shape)
        counter = {"n": 0, "traced": 0}

        def read(env, v):
            return v.val if isinstance(v, jexc.Literal) else env[id(v)]

        def maybe_trace(eqn, prefix, outs, iteration):
            """Attach trace points to an equation's numeric outputs."""
            prim = eqn.primitive
            src = source_info_util.summarize(eqn.source_info)
            for j, (var, val) in enumerate(zip(eqn.outvars, outs)):
                if not _numeric_aval(var.aval):
                    continue
                idx = counter["n"]
                counter["n"] += 1
                tag = "" if len(eqn.outvars) == 1 else f".{j}"
                name = f"{idx:04d} {prefix}{prim.name}{tag} {src}"
                if op_re and not op_re.search(prim.name):
                    continue
                if name_re and not name_re.search(name):
                    continue
                if (max_traced is not None
                        and counter["traced"] >= max_traced):
                    continue
                counter["traced"] += 1
                outs[j] = trace_point(name, val, enabled=True,
                                      iteration=iteration)
            return outs

        def closed_parts(sub):
            if hasattr(sub, "jaxpr"):          # ClosedJaxpr
                return sub.jaxpr, sub.consts
            return sub, []

        def eval_scan(eqn, invals, prefix, iteration):
            """Re-stage a scan with its body instrumented ONCE; the
            carried counter tags every trip's stats."""
            p = eqn.params
            body_jaxpr, body_consts = closed_parts(p["jaxpr"])
            nc, ncarry = p["num_consts"], p["num_carry"]
            consts_in = invals[:nc]
            carry_in = invals[nc:nc + ncarry]
            xs = invals[nc + ncarry:]

            def body_fn(carry_it, x):
                carry, it = carry_it
                outs = eval_jaxpr(body_jaxpr, body_consts,
                                  [*consts_in, *carry, *x],
                                  f"{prefix}scan/", iteration=it)
                return (outs[:ncarry], it + 1), outs[ncarry:]

            (carry_out, _), ys = jax.lax.scan(
                body_fn, (list(carry_in), jnp.int32(0)), list(xs),
                length=p["length"], reverse=p["reverse"],
                unroll=p.get("unroll", 1))
            return [*carry_out, *ys]

        def eval_while(eqn, invals, prefix, iteration):
            """Re-stage a while_loop: the body is instrumented (with a
            trip counter smuggled into the carry); the COND stays
            uninstrumented — it must remain effect-free."""
            p = eqn.params
            cond_jaxpr, cond_consts = closed_parts(p["cond_jaxpr"])
            body_jaxpr, body_consts = closed_parts(p["body_jaxpr"])
            cn, bn = p["cond_nconsts"], p["body_nconsts"]
            cconsts = invals[:cn]
            bconsts = invals[cn:cn + bn]
            init = list(invals[cn + bn:])

            def cond_fn(state):
                carry, _it = state
                from jax.extend.core import jaxpr_as_fun
                from jax.extend import core as _jexc
                closed = _jexc.ClosedJaxpr(cond_jaxpr, cond_consts)
                return jaxpr_as_fun(closed)(*cconsts, *carry)[0]

            def body_fn(state):
                carry, it = state
                outs = eval_jaxpr(body_jaxpr, body_consts,
                                  [*bconsts, *carry],
                                  f"{prefix}while/", iteration=it)
                return (outs, it + 1)

            carry_out, _ = jax.lax.while_loop(
                cond_fn, body_fn, (init, jnp.int32(0)))
            return list(carry_out)

        def eval_cond(eqn, invals, prefix, iteration):
            """Re-stage lax.cond/switch with every branch
            instrumented."""
            index, *ops = invals
            branches = [closed_parts(b) for b in eqn.params["branches"]]

            def make_branch(k, bj, bc):
                return lambda *a: eval_jaxpr(
                    bj, bc, list(a), f"{prefix}branch{k}/",
                    iteration=iteration)

            return jax.lax.switch(
                index, [make_branch(k, bj, bc)
                        for k, (bj, bc) in enumerate(branches)], *ops)

        def eval_jaxpr(jaxpr, consts, args, prefix, iteration=None):
            env: dict = {}
            for v, c in zip(jaxpr.constvars, consts):
                env[id(v)] = c
            for v, a in zip(jaxpr.invars, args):
                env[id(v)] = a
            for eqn in jaxpr.eqns:
                prim = eqn.primitive
                invals = [read(env, v) for v in eqn.invars]
                sub = None
                if prim.name in _CALL_PRIMITIVES:
                    sub = (eqn.params.get("jaxpr")
                           or eqn.params.get("call_jaxpr")
                           or eqn.params.get("fun_jaxpr"))
                if prim.name == "scan":
                    outs = eval_scan(eqn, invals, prefix, iteration)
                elif prim.name == "while":
                    outs = eval_while(eqn, invals, prefix, iteration)
                elif prim.name == "cond":
                    outs = eval_cond(eqn, invals, prefix, iteration)
                elif sub is not None:
                    sub_jaxpr, sub_consts = closed_parts(sub)
                    sub_name = eqn.params.get("name", prim.name)
                    outs = eval_jaxpr(sub_jaxpr, sub_consts, invals,
                                      f"{prefix}{sub_name}/",
                                      iteration=iteration)
                else:
                    outs = prim.bind(*invals, **eqn.params)
                    if not prim.multiple_results:
                        outs = [outs]
                    if prim.name not in _SKIP_PRIMITIVES:
                        outs = maybe_trace(eqn, prefix, outs, iteration)
                for var, val in zip(eqn.outvars, outs):
                    env[id(var)] = val
            return [read(env, v) for v in jaxpr.outvars]

        flat_out = eval_jaxpr(closed.jaxpr, closed.consts, flat_args, "")
        return jax.tree_util.tree_unflatten(out_tree, flat_out)

    return wrapped


def trace_fn(fn: Callable, *args, report_path: "str | None" = None,
             op_regex: "str | None" = None,
             name_regex: "str | None" = None,
             max_traced: "int | None" = None, **kwargs):
    """One-shot whole-program trace: run ``fn(*args, **kwargs)`` fully
    instrumented, return ``(outputs, TraceReport)`` and optionally write
    the report file (≙ tensor_tracer_report.py's on-disk report).

        out, report = trace_fn(train_step, state, batch,
                               report_path="/tmp/tt/report.txt")
        report.first_nan()   # "0042 layers/mul <file>:<line> ..." or None
    """
    inst = instrument(fn, op_regex=op_regex, name_regex=name_regex,
                      max_traced=max_traced)
    tt = TensorTracer()
    with tt:
        out = inst(*args, **kwargs)
        out = jax.block_until_ready(out)
    report = tt.report()
    if report_path is not None:
        report.write(report_path)
    return out, report
