"""bfloat16 mixed-precision policy + scope.

≙ tensorflow/python/tpu/bfloat16.py (:71 ``bfloat16_scope`` — a variable
scope whose custom getter stores variables in fp32 and serves bf16 casts
to compute; SURVEY.md §2.6). The TPU-native form is a thread-local
POLICY (compute dtype / variable dtype) plus explicit cast helpers:
storage stays fp32 (master weights), compute reads cast to bf16 — the
exact split the models in this package implement via their ``dtype``
configs, exposed here as the reference-shaped API.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
from typing import Any

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Policy:
    """≙ keras mixed_precision.Policy / the bfloat16_scope contract."""
    name: str
    compute_dtype: Any
    variable_dtype: Any


POLICIES = {
    "float32": Policy("float32", jnp.float32, jnp.float32),
    "mixed_bfloat16": Policy("mixed_bfloat16", jnp.bfloat16, jnp.float32),
    "bfloat16": Policy("bfloat16", jnp.bfloat16, jnp.bfloat16),
}

# Global default (set_global_policy: visible to ALL threads — worker
# lanes, infeed threads) + a thread-local scope stack for `with` blocks.
_GLOBAL = {"policy": POLICIES["float32"]}
_SCOPES = threading.local()


def _scope_stack() -> list:
    if not hasattr(_SCOPES, "stack"):
        _SCOPES.stack = []
    return _SCOPES.stack


def get_policy() -> Policy:
    stack = _scope_stack()
    return stack[-1] if stack else _GLOBAL["policy"]


def set_global_policy(policy: "Policy | str"):
    _GLOBAL["policy"] = (POLICIES[policy] if isinstance(policy, str)
                         else policy)


@contextlib.contextmanager
def policy_scope(policy: "Policy | str"):
    p = POLICIES[policy] if isinstance(policy, str) else policy
    _scope_stack().append(p)
    try:
        yield p
    finally:
        _scope_stack().pop()


@contextlib.contextmanager
def bfloat16_scope():
    """≙ tpu.bfloat16_scope (bfloat16.py:71): compute in bf16, variables
    stored fp32. Usage::

        with bfloat16_scope():
            y = model_fn(cast_to_compute(x), params)
    """
    with policy_scope("mixed_bfloat16") as p:
        yield p


def compute_dtype():
    return get_policy().compute_dtype


def variable_dtype():
    return get_policy().variable_dtype


def cast_to_compute(tree):
    """Cast floating leaves to the active compute dtype (≙ the scope's
    custom-getter cast on variable reads)."""
    dt = compute_dtype()

    def cast(x):
        x = jnp.asarray(x)
        return x.astype(dt) if jnp.issubdtype(x.dtype, jnp.floating) else x

    return jax.tree_util.tree_map(cast, tree)


def cast_to_variable(tree):
    """Cast floating leaves to the storage dtype (master copy)."""
    dt = variable_dtype()

    def cast(x):
        x = jnp.asarray(x)
        return x.astype(dt) if jnp.issubdtype(x.dtype, jnp.floating) else x

    return jax.tree_util.tree_map(cast, tree)
